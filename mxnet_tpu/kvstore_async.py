"""Asynchronous parameter server for ``dist_async`` (parity: reference
``src/kvstore/kvstore_dist_server.h:136-205`` async ``DataHandle`` +
``kvstore.cc:32`` + multi-server key layout ``kvstore_dist.h:269-300``).

Observable semantics match the reference's async mode:

* **update-on-push** — the server applies the optimizer the moment a
  worker's gradient arrives; there is no cross-worker aggregation and no
  barrier, so workers progress independently and fast workers see (and
  compound) updates that slow workers haven't contributed to yet
  (bounded-by-nothing staleness, exactly ps-lite's behavior).
* **server-side optimizer** — ``set_optimizer`` pickles the optimizer to
  every server (reference ``kvstore.py:226`` / ``kSetOptimizer``), which
  owns the authoritative weights.
* **pull-anytime** — a pull returns the server's current weight, however
  stale the puller is.
* **multi-server topology** — keys are sharded across N servers by hash
  (reference ``EncodeKey``), and big arrays are **striped**: split into N
  contiguous flat chunks, one per server, so no single server carries a
  whole embedding table (reference ``kvstore_dist.h:44`` ``bigarray_bound_``
  + ``:269-300``).  ``tools/launch.py -s N`` starts real server processes;
  without it, a thread inside rank-0 hosts a single server (the TPU-native
  degenerate layout — sync mode needs no host data plane at all).

**Replication (hot standby)**: each logical shard may be a replica
*group* — one primary plus hot-standby follower(s).  The primary applies
every mutating op, stamps a per-key sequence number, appends the op to a
replication log, and streams it to each follower over a dedicated sender
thread (``MXNET_TPU_KV_REPL_SYNC=1`` makes the primary wait for follower
acks before answering the worker, trading latency for zero-loss
failover).  Membership is epoch-numbered: a promotion bumps the epoch,
and both stale clients and zombie ex-primaries are *fenced* — their
writes are rejected with a typed ``StaleEpochError`` rather than
silently forking the weights.  :class:`ReplicatedClient` routes a
worker's traffic to the current primary, detects death via heartbeats or
failed RPCs, promotes a follower, and transparently retries the
in-flight request with the SAME sequence number (the replicated
per-worker dedup cache makes the retry at-most-once even across a
failover).  A restarted server calls :meth:`AsyncServer.rejoin` to
state-transfer a snapshot (weights + per-key seqnos + optimizer state)
from the current primary and re-enter the group as a follower.

Wire format (hardened, round-3): length-framed **JSON header + raw tensor
buffers** — nothing on the data path is executable, unlike pickle.  Tensor
byte-lengths are derived from dtype+shape, so a corrupt header cannot
over-read.  The ONE pickle left is the ``set_optimizer`` payload and the
optimizer state inside a replication snapshot (the reference ships a
pickled optimizer too); both are gated by an HMAC-SHA256 with a per-job
shared secret carried over the same trusted channel as the server
address (launcher env / jax.distributed coordination KV), so a bare
TCP connection cannot inject code.  Message size is capped
(``MXNET_TPU_PS_MAX_MSG_MB``).  A frame cut mid-read surfaces as a typed
:class:`TruncatedMessageError` (an ``EOFError`` subclass, so the retry
path handles it), never as garbage handed to the decoder.

Environment tunables (all read LAZILY, per call — a test or job can
reconfigure any of them without re-importing the module):

=============================  =========  ==================================
variable                       default    meaning
=============================  =========  ==================================
``MXNET_TPU_PS_DEAD_AFTER``    ``30``     seconds without contact before a
                                          peer (worker or primary) counts
                                          as dead
``MXNET_TPU_PS_HEARTBEAT``     dead/3     worker heartbeat base interval
                                          (floor 1 s unless set explicitly)
``MXNET_TPU_PS_CALL_TIMEOUT``  ``60``     per-attempt socket timeout for
                                          one RPC round trip
``MXNET_TPU_PS_DEADLINE``      ``120``    overall per-RPC deadline across
                                          retries → ``ServerDeadError``
``MXNET_TPU_PS_INIT_TIMEOUT``  ``120``    init-barrier poll timeout
``MXNET_TPU_PS_MAX_MSG_MB``    ``1024``   wire-frame size cap
``MXNET_TPU_KV_REPLICAS``      ``1``      replicas per logical shard in the
                                          degenerate in-process layout
``MXNET_TPU_KV_REPL_SYNC``     ``0``      1 = primary waits for follower
                                          acks before answering a mutation
                                          (exact failover, ~1 RTT extra)
``MXNET_TPU_KV_REPL_TIMEOUT``  ``10``     sync-mode ack wait bound; past
                                          it the primary answers anyway
                                          and the entry stays queued
``MXNET_KVSTORE_BIGARRAY_``    ``1e6``    striping threshold in elements
``BOUND``                                 (job-wide; decides routing)
``MXNET_TPU_PS_SECRET``        —          per-job HMAC secret for the
                                          pickled-optimizer payloads
``MXNET_TPU_PS_HOST``          —          opt-in non-loopback bind host
``MXNET_TPU_ASYNC_PS_ADDR``    —          explicit server address override
``MXNET_TPU_ASYNC_PS_ADDRS``   —          comma-separated shard list; each
                                          shard may be a ``|``-separated
                                          replica group (``a|b,c|d``)
=============================  =========  ==================================
"""

from __future__ import annotations

import base64 as _b64
import collections
import hashlib
import hmac as _hmaclib
import itertools
import json as _json
import logging
import os
import pickle
import random as _random
import secrets as _secrets
import socket
import socketserver
import struct
import threading
import time
import zlib

import numpy as _np

from . import chaos as _chaos
from . import kvstore_wire as _wire
from .base import (CorruptMessageError, MXNetError, ServerDeadError,
                   ShardFailedError, StaleEpochError,
                   TruncatedMessageError)
from .kvstore_wire import _unwire_key, _wire_key
from .observability import metrics as _metrics
from .observability import tracing as _tracing
from .observability import flight_recorder as _flight

__all__ = ["AsyncServer", "AsyncClient", "ReplicatedClient", "ServerGroup",
           "ServerDeadError", "ShardFailedError", "StaleEpochError",
           "TruncatedMessageError", "CorruptMessageError",
           "publish_address", "lookup_address", "reset_membership"]

_KV_KEY = "mxtpu_async_ps_addr"

_LOG = logging.getLogger(__name__)

# -- observability families (handles resolved once at import; labeled
# children are cached inside the family, so per-event cost is one dict
# lookup + method call, and zero work under MXNET_TPU_METRICS=0) -----------
_M_RPC = _metrics.histogram(
    "kv_rpc_seconds", "Worker-side RPC latency (retries included)",
    ["op"])
_M_HB_AGE = _metrics.gauge(
    "kv_heartbeat_age_seconds",
    "Seconds since this worker's last successful heartbeat probe",
    ["server"])
_M_FAILOVER = _metrics.counter(
    "kv_failover_total",
    "Successful client-driven failovers (standby promoted to primary)")
# badput sources: the goodput ledger (observability/efficiency.py) turns
# in-fit deltas of these into badput_seconds_total{cause=kv_retry|failover}.
# Background-thread ops (heartbeat) and failover-internal ops (promote —
# its wall is already inside kv_failover_seconds_total) are excluded so
# the causes stay disjoint subsets of the fit loop's step wall.
_RETRY_UNACCOUNTED_OPS = frozenset(("heartbeat", "promote"))
_M_RETRY_S = _metrics.counter(
    "kv_retry_seconds_total",
    "Wall seconds worker RPCs spent in the retry/backoff window after a "
    "transport failure, from first failure to final outcome (heartbeat "
    "and promote ops excluded)")
_M_FAILOVER_S = _metrics.counter(
    "kv_failover_seconds_total",
    "Wall seconds spent inside client-driven failover attempts, "
    "successful or not")
_M_FENCED = _metrics.counter(
    "kv_fenced_total",
    "Primaries demoted to role 'fenced' after meeting a higher epoch")
_M_REJOIN = _metrics.counter(
    "kv_rejoin_total",
    "Servers that re-entered a replica group via live state transfer")
_M_REPL_LAG = _metrics.gauge(
    "kv_replication_lag",
    "Primary log entries not yet acked by the follower (seqno delta)",
    ["follower"])
_M_SERVE = _metrics.histogram(
    "kv_serve_seconds",
    "Server-side request handling latency by op and serving shard "
    "(REPL_SYNC follower-ack waits included; heartbeat/stats probes "
    "excluded) — the federation derives per-shard straggler skew from "
    "these series", ["op", "server"])
# -- wire-bandwidth books (PR 15).  Byte accounting happens at the
# _send_msg/_recv_msg seams, where the whole frame is in hand: the
# header part is the 8-byte outer length prefix + 4-byte header length
# + JSON header, the payload part is the raw tensor/opaque blobs, so
# header+payload sums to exactly what the socket carries and the books
# are falsifiable against kv_socket_bytes_total (tools/wire_report.py
# exits nonzero when they drift past 1%).
_WIRE_FRAME_BUCKETS = (64.0, 256.0, 1024.0, 4096.0, 16384.0, 65536.0,
                       262144.0, 1048576.0, 4194304.0, 16777216.0)
_RPCS_FLUSH_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0,
                       24.0, 32.0, 48.0, 64.0)
_M_WIRE_BYTES = _metrics.counter(
    "kv_wire_bytes_total",
    "Bytes crossing the kvstore wire by op, direction (send/recv on the "
    "RPC path, replicate on the primary→follower log), and frame part "
    "(header = length prefixes + JSON header; payload = raw blobs). "
    "Decode failures book the consumed prefix once under op='corrupt'",
    ["op", "dir", "part"])
_M_WIRE_FRAME = _metrics.histogram(
    "kv_wire_frame_bytes",
    "Full wire-frame size (outer length prefix included) per message",
    ["op", "dir"], buckets=_WIRE_FRAME_BUCKETS)
_M_WIRE_RPCS = _metrics.histogram(
    "kv_wire_rpcs_per_flush",
    "Wire RPCs one logical ServerGroup flush (a push or a pull) costs. "
    "Uncoalesced, that is the per-server fan-out width; a coalesced "
    "push_pull amortizes its fused RPCs across both logical flushes, "
    "so the p50 falling is the measured coalescing win",
    buckets=_RPCS_FLUSH_BUCKETS)
_M_COALESCE_SAVED = _metrics.counter(
    "kv_coalesce_rpcs_saved_total",
    "Wire RPCs avoided by fusing a step's push+pull flushes into one "
    "push_pull per shard (baseline fan-out minus fused fan-out)")
_M_WIRE_CODEC = _metrics.histogram(
    "kv_wire_codec_seconds",
    "Wall seconds serializing (stage=encode) or deserializing "
    "(stage=decode) one wire frame — the CPU tax a zero-copy binary "
    "wire would remove", ["op", "stage"])
_M_SOCK_BYTES = _metrics.counter(
    "kv_socket_bytes_total",
    "Socket-level ground truth: bytes actually handed to send() or "
    "returned by recv() on kvstore sockets, by direction — the book "
    "kv_wire_bytes_total must reconcile against", ["dir"])
_H_SOCK_SEND = _M_SOCK_BYTES.labels("send")
_H_SOCK_RECV = _M_SOCK_BYTES.labels("recv")
# per-thread scratch for the kv.rpc span attrs (bytes/encode_us): the
# seams run under the span but deep in the call stack, so they drop the
# numbers here and AsyncClient._call picks them up after _call_impl.
_WIRE_TLS = threading.local()


# -- tunables, read LAZILY so jobs and tests can reconfigure timeouts
# through the environment without re-importing the module (see the table
# in the module docstring) -------------------------------------------------

def _dead_after_s():
    """Seconds without a heartbeat before a worker counts as dead."""
    return float(os.environ.get("MXNET_TPU_PS_DEAD_AFTER", "30"))


def _heartbeat_interval_s():
    """Worker heartbeat base interval; defaults to a third of the death
    window (floored at 1 s so idle workers don't spin)."""
    env = os.environ.get("MXNET_TPU_PS_HEARTBEAT")
    if env:
        return float(env)
    return max(_dead_after_s() / 3.0, 1.0)


def _max_msg_bytes():
    """Wire-frame size cap."""
    return int(os.environ.get("MXNET_TPU_PS_MAX_MSG_MB", "1024")) << 20


def _coalesce_enabled():
    """RPC coalescing: fuse each training step's push+pull flushes into
    one push_pull RPC per shard (``MXNET_TPU_KV_COALESCE=0`` restores
    the two-round-trip path)."""
    return os.environ.get("MXNET_TPU_KV_COALESCE", "1") != "0"


def _call_timeout_s():
    """Per-attempt socket timeout for one RPC round trip."""
    return float(os.environ.get("MXNET_TPU_PS_CALL_TIMEOUT", "60"))


def _deadline_s():
    """Overall per-RPC deadline across all retries; when it expires the
    server is declared dead (``ServerDeadError``)."""
    return float(os.environ.get("MXNET_TPU_PS_DEADLINE", "120"))


def _replicas():
    """Replicas per logical shard in the degenerate in-process layout."""
    return max(1, int(os.environ.get("MXNET_TPU_KV_REPLICAS", "1")))


def _repl_sync():
    """Whether the primary waits for follower acks before responding."""
    return os.environ.get("MXNET_TPU_KV_REPL_SYNC", "0").lower() \
        not in ("0", "false", "")


def _repl_timeout_s():
    """Sync-mode bound on the follower-ack wait."""
    return float(os.environ.get("MXNET_TPU_KV_REPL_TIMEOUT", "10"))


# ops whose effect is not idempotent: dedup must cache their responses so
# a retry is answered from cache, never re-applied.  pulls/stats re-execute.
# promote/sync_follower are membership ops and idempotent by construction
# (same-epoch promote acks; re-sync re-snapshots), so they stay out.
# The three resize_* mutations are the elastic re-striping protocol
# (``elastic.ResizePlan``): install stages a key on its new owner, retire
# freezes+exports it on the old owner (leaving a ``StaleEpochError``
# tombstone), discard rolls a staged copy back — all three replicate so a
# follower promoted mid-resize holds the same tombstones and staged keys.
_MUTATING_OPS = frozenset({"init", "push", "push_pull", "set_optimizer",
                           "command", "resize_install", "resize_retire",
                           "resize_discard", "resize_seal"})
# the same ops are what a primary appends to its replication log
_REPLICATED_OPS = _MUTATING_OPS


# -- wire codecs ----------------------------------------------------------
#
# Two frame formats share the 8-byte outer length prefix:
#
# * the PR-17 BINARY frame (kvstore_wire.py): fixed 54-byte header +
#   key table + tensor descriptors + zero-copy raw payload — the
#   default (``MXNET_TPU_KV_WIRE=binary``);
# * the PR-15 JSON frame below (``MXNET_TPU_KV_WIRE=json``), kept one
#   release for interop.
#
# Decode auto-detects by magic, and a server answers in the format the
# request arrived in, so an old-format peer on either end of the socket
# keeps working without negotiation.

def _encode_msg(msg):
    """Serialize a message dict.  Tensors (under ``pairs``/``vals``) and
    the opaque ``optimizer`` bytes become appended raw buffers; everything
    else must be JSON-safe."""
    header = {}
    blobs = []

    def tensor_ref(v):
        if v is None:
            return None
        arr = _np.ascontiguousarray(v)
        blobs.append(arr.tobytes())
        return {"dtype": str(arr.dtype), "shape": list(arr.shape)}

    for field, value in msg.items():
        if field == "pairs":
            header[field] = [[_wire_key(k), tensor_ref(v)] for k, v in value]
        elif field == "vals":
            header[field] = [tensor_ref(v) for v in value]
        elif field == "keys":
            header[field] = [_wire_key(k) for k in value]
        elif field == "optimizer":
            raw = bytes(value)
            blobs.append(raw)
            header[field] = {"rawlen": len(raw)}
        else:
            header[field] = value
    hdr = _json.dumps(header, separators=(",", ":")).encode("utf-8")
    return b"".join([struct.pack("<I", len(hdr)), hdr] + blobs)


def _decode_msg(payload):
    """Inverse of :func:`_encode_msg`.  Buffer lengths come from
    dtype+shape (or the recorded rawlen), never from attacker-elastic
    framing."""
    (hdr_len,) = struct.unpack_from("<I", payload, 0)
    header = _json.loads(payload[4:4 + hdr_len].decode("utf-8"))
    cursor = [4 + hdr_len]

    def take(n):
        start = cursor[0]
        if start + n > len(payload):
            raise CorruptMessageError("truncated message")
        cursor[0] = start + n
        return payload[start:start + n]

    def tensor_of(ref):
        if ref is None:
            return None
        dtype = _np.dtype(ref["dtype"])
        shape = tuple(int(d) for d in ref["shape"])
        count = 1
        for d in shape:
            count *= d
        raw = take(count * dtype.itemsize)
        return _np.frombuffer(raw, dtype=dtype).reshape(shape).copy()

    msg = {}
    for field, value in header.items():
        if field == "pairs":
            msg[field] = [(_unwire_key(k), tensor_of(ref)) for k, ref in value]
        elif field == "vals":
            msg[field] = [tensor_of(ref) for ref in value]
        elif field == "keys":
            msg[field] = [_unwire_key(k) for k in value]
        elif field == "optimizer":
            msg[field] = take(int(value["rawlen"]))
        else:
            msg[field] = value
    return msg


class _MessageTooBig(ValueError):
    pass


def _sendall(sock, data):
    """``sendall`` with explicit partial-write bookkeeping: an ``EINTR``
    mid-frame resumes from the exact byte reached, never re-sends a
    prefix (which would desynchronize the length-framed stream)."""
    view = memoryview(data)
    sent = 0
    while sent < len(view):
        try:
            n = sock.send(view[sent:])
        except InterruptedError:
            continue  # PEP 475 covers most of these; belt and braces
        sent += n
        _H_SOCK_SEND.inc(float(n))


def _recv_exact(sock, n, what):
    """Read exactly ``n`` bytes, retrying short reads and ``EINTR``.
    A peer that dies mid-frame raises :class:`TruncatedMessageError`
    (typed, retriable) instead of handing a short buffer to the
    decoder."""
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(min(1 << 20, n - len(buf)))
        except InterruptedError:
            continue
        if not chunk:
            if not buf and what == "frame header":
                raise EOFError("peer closed")  # clean close between frames
            raise TruncatedMessageError(
                "peer closed after %d of %d bytes of %s — frame truncated"
                % (len(buf), n, what))
        buf += chunk
        _H_SOCK_RECV.inc(float(len(chunk)))
    return bytes(buf)


def _record_wire(op, dirn, stage, codec_s, payload):
    """Book one frame into the wire families.  ``payload`` is the framed
    body WITHOUT the 8-byte outer length prefix; the prefix is charged to
    the header part so header+payload equals the socket bytes exactly.
    The header/payload split is format-aware: binary frames carry their
    own header length in a fixed slot (O(1)), JSON frames derive it from
    the u32 header-length prefix."""
    frame = 8 + len(payload)
    if _wire.is_binary_frame(payload):
        hdr_len = _wire.header_len(payload)
        header_b = min(8 + hdr_len, frame)
    else:
        (hdr_len,) = struct.unpack_from("<I", payload, 0)
        header_b = min(8 + 4 + hdr_len, frame)
    _M_WIRE_BYTES.labels(op, dirn, "header").inc(float(header_b))
    _M_WIRE_BYTES.labels(op, dirn, "payload").inc(float(frame - header_b))
    _M_WIRE_FRAME.labels(op, dirn).observe(float(frame))
    _M_WIRE_CODEC.labels(op, stage).observe(codec_s)


def _send_msg(sock, obj, *, op=None, wire_dir="send", fmt=None):
    """``fmt`` pins the frame format (a server answers in the format the
    request arrived in); None defers to ``MXNET_TPU_KV_WIRE``."""
    rec = _metrics.metrics_enabled()
    trace = _tracing.tracing_enabled()
    if fmt is None:
        fmt = _wire.wire_format()
    t0 = time.monotonic() if (rec or trace) else 0.0
    payload = (_wire.encode_frame(obj) if fmt == "binary"
               else _encode_msg(obj))
    codec_s = (time.monotonic() - t0) if (rec or trace) else 0.0
    cap = _max_msg_bytes()
    if len(payload) > cap:
        # refuse locally: the peer would cut the connection mid-frame and
        # a blind retry would just resend the same oversized message
        raise _MessageTooBig(
            "message of %d bytes exceeds MXNET_TPU_PS_MAX_MSG_MB=%d — "
            "raise the cap or shrink/stripe the arrays"
            % (len(payload), cap >> 20))
    # chaos site: drop raises ConnectionResetError (the retry path's
    # exception), corrupt garbles the outgoing frame payload.  Books come
    # after the visit so dropped frames never reach the ledger.
    payload = _chaos.visit("kvstore.send", payload)
    if rec:
        _record_wire(str(op if op is not None else (obj.get("op") or "resp")),
                     wire_dir, "encode", codec_s, payload)
    if trace:
        _WIRE_TLS.bytes_out = 8 + len(payload)
        _WIRE_TLS.encode_us = codec_s * 1e6
    _sendall(sock, struct.pack("<Q", len(payload)) + payload)


def _recv_msg(sock, *, op=None, wire_dir="recv"):
    rec = _metrics.metrics_enabled()
    trace = _tracing.tracing_enabled()
    hdr = _recv_exact(sock, 8, "frame header")
    (n,) = struct.unpack("<Q", hdr)
    if n > _max_msg_bytes():
        if rec:
            # only the 8-byte prefix was consumed; book it once here —
            # the caller tears the connection down, never re-reads it
            _M_WIRE_BYTES.labels("corrupt", wire_dir, "header").inc(8.0)
        raise CorruptMessageError(
            "message of %d bytes exceeds MXNET_TPU_PS_MAX_MSG_MB" % n)
    buf = _recv_exact(sock, n, "frame body")
    # chaos site AFTER the frame is fully consumed: a drop models the
    # response lost in flight (the socket is torn down either way), a
    # corrupt models bit-rot — decode rejects it via length/JSON checks
    buf = _chaos.visit("kvstore.recv", buf)
    t0 = time.monotonic() if (rec or trace) else 0.0
    try:
        # magic-based auto-detect: binary frames (incl. any future
        # version byte, rejected typed) vs the one-release JSON frame
        if _wire.is_binary_frame(buf):
            msg = _wire.decode_frame(bytes(buf))
            _WIRE_TLS.rx_fmt = "binary"
        else:
            msg = _decode_msg(bytes(buf))
            _WIRE_TLS.rx_fmt = "json"
    except Exception:
        if rec:
            # the frame WAS consumed off the socket; book the prefix+body
            # exactly once under op='corrupt'.  The retry opens a fresh
            # frame with its own accounting — no double count.
            _M_WIRE_BYTES.labels("corrupt", wire_dir, "header").inc(8.0)
            _M_WIRE_BYTES.labels("corrupt", wire_dir, "payload").inc(float(n))
        raise
    codec_s = (time.monotonic() - t0) if (rec or trace) else 0.0
    rop = str(op if op is not None else (msg.get("op") or "resp"))
    if rec:
        _record_wire(rop, wire_dir, "decode", codec_s, buf)
    if trace:
        _WIRE_TLS.bytes_in = 8 + n
        _WIRE_TLS.decode_us = codec_s * 1e6
    return msg


def _optimizer_mac(secret, raw):
    return _hmaclib.new(secret.encode("utf-8"), raw, hashlib.sha256).hexdigest()


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: AsyncServer = self.server.owner  # type: ignore[attr-defined]
        srv._track_conn(self.request)
        try:
            while True:
                msg = _recv_msg(self.request)
                # answer in the format the request arrived in: an
                # old-format (JSON) client never sees a binary frame
                fmt = getattr(_WIRE_TLS, "rx_fmt", None)
                resp = srv.dispatch(msg)
                op = msg.get("op")
                try:
                    _send_msg(self.request, resp, op=op, fmt=fmt)
                except _MessageTooBig as exc:
                    # tell the client WHY instead of dying mid-frame (a
                    # bare cut would read as 'peer closed' after retries)
                    _send_msg(self.request, {"ok": False, "err": str(exc)},
                              op=op, fmt=fmt)
        except (EOFError, ConnectionError, ValueError, OSError):
            return
        finally:
            srv._untrack_conn(self.request)


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def _default_bind_host():
    """Loopback unless the operator explicitly opts into multi-host via
    ``MXNET_TPU_PS_HOST``: even with the non-executable wire format the
    listener should not face arbitrary networks by default."""
    return "0.0.0.0" if os.environ.get("MXNET_TPU_PS_HOST") else "127.0.0.1"


def _advertise_host(bind_host):
    """The address workers should dial for a server bound to
    ``bind_host``: the bind host itself when it names an interface; for
    wildcard binds, ``MXNET_TPU_PS_HOST`` or this host's resolvable name."""
    if bind_host not in ("0.0.0.0", "", "::"):
        return bind_host
    env = os.environ.get("MXNET_TPU_PS_HOST")
    if env:
        return env
    try:
        name = socket.gethostname()
        socket.getaddrinfo(name, None)
        return name
    except OSError:
        return "127.0.0.1"


class _AckLatch:
    """Completion latch for one replicated entry in sync mode: released
    once every (live) follower has acked the entry."""

    def __init__(self, n, rseq):
        self.rseq = rseq
        self._n = n
        self._lock = threading.Lock()
        self._evt = threading.Event()
        if n <= 0:
            self._evt.set()

    def ack(self):
        with self._lock:
            self._n -= 1
            if self._n <= 0:
                self._evt.set()

    def wait(self, timeout):
        return self._evt.wait(timeout)


class _FollowerLink:
    """Primary-side replication channel to ONE follower: an ordered queue
    of applied-entry messages drained by a dedicated sender thread.
    Entries are popped only on follower ack, so a dropped frame is simply
    re-sent (the follower dedups by log seqno); a follower unreachable
    past the death window is dropped from the group and the primary
    continues solo."""

    _RETRY_BASE_S = 0.05
    _RETRY_CAP_S = 1.0

    def __init__(self, owner, addr):
        self.addr = addr
        self.alive = True
        self.acked_rseq = 0
        self._owner = owner
        host, port = addr.rsplit(":", 1)
        self._peer = (host, int(port))
        self._q = collections.deque()
        self._cv = threading.Condition()
        self._thread = threading.Thread(
            target=self._run, name="mxtpu-ps-repl-s%d" % owner.server_id,
            daemon=True)
        self._thread.start()

    def enqueue(self, entry, latch):
        with self._cv:
            if not self.alive:
                if latch is not None:
                    latch.ack()
                return
            self._q.append((entry, latch))
            self._cv.notify()

    def close(self):
        """Stop the sender; pending sync latches are released (the
        caller's wait must not outlive the follower)."""
        with self._cv:
            self.alive = False
            for _entry, latch in self._q:
                if latch is not None:
                    latch.ack()
            self._q.clear()
            self._cv.notify()

    @staticmethod
    def _close_sock(sock):
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _run(self):
        sock = None
        failures = 0
        down_since = None
        while True:
            with self._cv:
                while self.alive and not self._q:
                    self._cv.wait(0.5)
                if not self.alive:
                    break
                entry, latch = self._q[0]
            label = "s%d>%s" % (self._owner.server_id, self.addr)
            try:
                # chaos sites: a delay stretches the replication lag
                # window, a drop loses this frame (retried, deduped by
                # rseq on the follower)
                _chaos.visit("kvstore.repl_delay", name=label)
                _chaos.visit("kvstore.repl_drop", name=label)
                if sock is None:
                    sock = socket.create_connection(
                        self._peer, timeout=_call_timeout_s())
                sock.settimeout(_call_timeout_s())
                out = dict(entry)
                out["epoch"] = self._owner.epoch
                # byte books ride under dir="replicate", labeled by the
                # replicated op (rop) so push traffic stays attributable
                _send_msg(sock, out, op=out.get("rop"), wire_dir="replicate")
                resp = _recv_msg(sock, op=out.get("rop"),
                                 wire_dir="replicate")
            except (EOFError, ConnectionError, OSError, ValueError) as exc:
                self._close_sock(sock)
                sock = None
                now = time.monotonic()
                if down_since is None:
                    down_since = now
                failures += 1
                if now - down_since >= _dead_after_s():
                    _LOG.warning(
                        "replication: follower %s unreachable for %.1fs — "
                        "dropping it from the group (last error: %r)",
                        self.addr, _dead_after_s(), exc)
                    break
                time.sleep(min(self._RETRY_CAP_S,
                               self._RETRY_BASE_S * (2 ** min(failures, 6))))
                continue
            failures = 0
            down_since = None
            if resp.get("ok"):
                with self._cv:
                    if self._q and self._q[0][0] is entry:
                        self._q.popleft()
                    self.acked_rseq = max(
                        self.acked_rseq,
                        int(resp.get("rseq", entry.get("rseq", 0))))
                _M_REPL_LAG.labels(self.addr).set(
                    max(self._owner._applied_seq - self.acked_rseq, 0))
                if latch is not None:
                    latch.ack()
            elif resp.get("resync"):
                # the follower has a gap (or diverged): ship a full
                # snapshot, then resend the entry — it dup-acks anything
                # the snapshot already covers
                with self._owner._lock:
                    snap = self._owner._snapshot_locked()
                snap["op"] = "replicate"
                snap["rop"] = "snapshot"
                try:
                    _send_msg(sock, snap, op="snapshot",
                              wire_dir="replicate")
                    sresp = _recv_msg(sock, op="snapshot",
                                      wire_dir="replicate")
                except (EOFError, ConnectionError, OSError,
                        ValueError):
                    self._close_sock(sock)
                    sock = None
                    continue
                if not sresp.get("ok"):
                    _LOG.warning(
                        "replication: follower %s rejected resync "
                        "snapshot: %s", self.addr, sresp.get("err"))
                    break
            elif resp.get("stale_epoch"):
                # the follower outranks us: this primary was deposed
                # while it still thought it owned the shard — fence it
                self._owner._fence(int(resp.get("epoch", 0)))
                break
            else:
                _LOG.warning(
                    "replication: follower %s rejected entry rseq=%s: %s",
                    self.addr, entry.get("rseq"), resp.get("err"))
                break
        self._close_sock(sock)
        self.close()
        self._owner._drop_follower(self.addr, self)


class AsyncServer:
    """One async PS shard: owns its keys' weights, applies updates on
    arrival.  ``server_id`` identifies the shard in a multi-server group.

    Replication roles: a server starts as the ``primary`` of a 1-replica
    group; :meth:`rejoin` turns it into a ``follower`` of an existing
    primary (snapshot transfer + live update stream), and a ``promote``
    RPC with a higher epoch turns a follower back into a primary.  A
    deposed primary that learns of a newer epoch becomes ``fenced``: it
    rejects all client traffic so a zombie cannot fork the weights."""

    def __init__(self, host=None, port=0, secret=None, server_id=0):
        host = host if host is not None else _default_bind_host()
        self._bind_host = host
        self.server_id = server_id
        # per-job shared secret gating the executable payloads
        # (set_optimizer pickle, snapshot optimizer state); generated
        # fresh unless the job hands one out (launcher env /
        # coordination KV).  Replicas of one shard must share it.
        self.secret = secret or os.environ.get("MXNET_TPU_PS_SECRET") \
            or _secrets.token_hex(16)
        self.role = "primary"
        self.epoch = 0
        self._store = {}
        self._seqnos = {}  # key -> per-key update sequence number
        # elastic re-striping tombstones: key -> {"epoch": N} for keys
        # retired to a new owner; any straggler access is rejected with
        # a typed StaleEpochError (moved=True) carrying that epoch
        self._moved = {}
        self._applied_seq = 0  # replication log position
        self._followers = {}  # follower addr -> _FollowerLink
        self._updater = None
        # the raw set_optimizer pickle, kept so a resize_export can
        # forward it: a shard that joins the job AFTER set_optimizer
        # (elastic scale-up) is configured by the plan from this copy
        self._opt_raw = None
        self._commands = []
        self._lock = threading.Lock()
        self._heartbeat = {}  # worker rank -> last contact time
        self._push_counts = {}  # worker rank -> pushes served
        # at-most-once RPC dedup for MUTATING ops only: rank -> (last seq,
        # cached response).  Pulls are idempotent and re-execute on retry,
        # so the server never retains a full response copy of the weights
        # per worker (round-2 advisor finding).  Replicated to followers,
        # so a request retried across a failover is still at-most-once.
        self._last_seq = {}
        self._shutdown = threading.Event()
        # in-flight dispatch tracking so stop() can drain gracefully: a
        # handler mid-update must finish (and its response flush) before
        # the listener is torn down, or the worker sees a half-applied
        # push it will retry against nothing
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        # open handler sockets: stop() severs them after the drain so a
        # stopped server is actually gone, not lingering on old
        # connections its daemon handler threads still serve
        self._conns = set()
        self._started = False
        self._stopped = False
        self._killed = False
        self._stop_lock = threading.Lock()
        self._tcp = _TCPServer((host, port), _Handler)
        self._tcp.owner = self
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="mxtpu-async-ps", daemon=True)

    @property
    def address(self):
        port = self._tcp.server_address[1]
        return "%s:%d" % (_advertise_host(self._bind_host), port)

    def start(self):
        with self._stop_lock:
            self._started = True
        self._thread.start()
        return self

    def stop(self, drain_timeout=5.0):
        """Stop accepting work, then DRAIN: wait (bounded) for in-flight
        dispatches to complete before closing the listener, so a handler
        mid-optimizer-update finishes and its response reaches the
        worker instead of being cut mid-frame.

        Idempotent: a second call (or a call on a server whose
        ``start()`` never ran / failed) returns immediately instead of
        hanging in ``socketserver.shutdown`` or double-closing the
        listener."""
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        with self._lock:
            links = list(self._followers.values())
            self._followers = {}
        for link in links:
            link.close()
        # shutdown() blocks on serve_forever's exit handshake, which
        # never happens if the serve thread was never started
        if self._started and self._thread.is_alive():
            self._tcp.shutdown()
        if drain_timeout > 0:
            deadline = time.monotonic() + drain_timeout
            with self._inflight_cv:
                while self._inflight:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        _LOG.warning(
                            "AsyncServer.stop: %d handler(s) still in flight "
                            "after %.1fs drain timeout", self._inflight,
                            drain_timeout)
                        break
                    self._inflight_cv.wait(remaining)
        for conn in list(self._conns):
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._tcp.server_close()

    def kill(self):
        """Abrupt crash (chaos / failover tests): no drain — in-flight
        handlers are cut mid-RPC, exactly what a process death looks
        like to the workers."""
        with self._stop_lock:
            self._killed = True
        self.stop(drain_timeout=0.0)

    def _track_conn(self, conn):
        with self._inflight_cv:
            self._conns.add(conn)

    def _untrack_conn(self, conn):
        with self._inflight_cv:
            self._conns.discard(conn)

    def wait_shutdown(self):
        """Block until a worker sends the ``shutdown`` op (server-process
        main loop)."""
        self._shutdown.wait()

    # -- replication ----------------------------------------------------

    def rejoin(self, primary_addr, dial_timeout=10):
        """State-transfer a snapshot from the CURRENT primary at
        ``primary_addr`` and re-enter its replica group as a follower.
        Registration and snapshot are atomic on the primary (one op
        under its lock), so every post-snapshot mutation reaches this
        server through the live update stream — there is no gap to
        catch up by other means."""
        cli = AsyncClient("%s" % primary_addr, -next(_rejoin_ranks),
                          heartbeat=False, secret=self.secret,
                          dial_timeout=dial_timeout)
        try:
            resp = cli._call({"op": "sync_follower", "addr": self.address})
        finally:
            cli.close()
        with self._lock:
            self._install_snapshot_locked(resp)
            self.role = "follower"
        _membership_note_replica(primary_addr, self.address)
        _M_REJOIN.inc()
        return self

    def _snapshot_locked(self):
        """Full state snapshot: weights, per-key seqnos, log position,
        dedup cache, push counts, and (HMAC-gated) optimizer state."""
        snap = {"pairs": [(k, _np.array(v)) for k, v in self._store.items()],
                "seqlist": [[_wire_key(k), int(n)]
                            for k, n in self._seqnos.items()],
                "moved": [[_wire_key(k), int(v["epoch"]),
                           v.get("addresses")]
                          for k, v in self._moved.items()],
                "rseq": self._applied_seq,
                "epoch": self.epoch,
                "last_seq": [[r, s, resp]
                             for r, (s, resp) in self._last_seq.items()],
                "push_counts": [[r, c]
                                for r, c in sorted(self._push_counts.items())]}
        if self._updater is not None:
            raw = pickle.dumps(self._updater._updater)
            snap["optimizer"] = raw
            snap["mac"] = _optimizer_mac(self.secret, raw)
        if self._opt_raw is not None:
            # rides base64 in the JSON header ("optimizer" is the one
            # binary codec field and it already carries the updater)
            snap["opt_raw_b64"] = _b64.b64encode(self._opt_raw).decode()
        return snap

    def _install_snapshot_locked(self, msg):
        raw = msg.get("optimizer")
        if raw is not None:
            if not _hmaclib.compare_digest(
                    msg.get("mac", ""), _optimizer_mac(self.secret, raw)):
                raise MXNetError(
                    "replication snapshot rejected: bad or missing HMAC on "
                    "the optimizer-state payload (replicas must share the "
                    "per-job secret)")
            self._updater = _NumpyUpdater(pickle.loads(raw))
        if msg.get("opt_raw_b64"):
            self._opt_raw = _b64.b64decode(msg["opt_raw_b64"])
        self._store = {k: _np.array(v, copy=True) for k, v in msg["pairs"]}
        self._seqnos = {_unwire_key(k): int(n)
                        for k, n in msg.get("seqlist", [])}
        self._moved = {}
        for entry in msg.get("moved", []):
            k, e, addrs = (entry + [None])[:3]
            t = {"epoch": int(e)}
            if addrs:
                t["addresses"] = list(addrs)
            self._moved[_unwire_key(k)] = t
        self._applied_seq = int(msg.get("rseq", 0))
        self.epoch = max(self.epoch, int(msg.get("epoch", 0)))
        self._last_seq = {int(r): (s, resp)
                          for r, s, resp in msg.get("last_seq", [])}
        self._push_counts = {int(r): int(c)
                             for r, c in msg.get("push_counts", [])}

    def _append_entry_locked(self, op, rank, seq, msg, resp):
        """Advance the replication log with one applied mutation and fan
        it out to the follower queues.  Returns the sync-mode ack latch
        (or None when async / no followers)."""
        self._applied_seq += 1
        entry = {"op": "replicate", "rop": op, "rseq": self._applied_seq,
                 "orank": rank, "oseq": seq, "resp": resp}
        # handler-thread context: the serve span (itself a child of the
        # worker's RPC span) parents the follower's replicate handling,
        # so replication shows up in the same trace tree
        trace_tok = _tracing.capture_wire_context()
        if trace_tok is not None:
            entry["trace"] = trace_tok
        if op in ("init", "push"):
            entry["pairs"] = msg["pairs"]
        elif op == "set_optimizer":
            entry["optimizer"] = msg["optimizer"]
            entry["mac"] = msg.get("mac", "")
        elif op == "resize_install":
            entry["pairs"] = msg["pairs"]
            entry["seqlist"] = msg.get("seqlist", [])
            if "optimizer" in msg:
                entry["optimizer"] = msg["optimizer"]
                entry["mac"] = msg.get("mac", "")
        elif op == "resize_retire":
            # "keys" is a codec field: _encode_msg wires it on send
            entry["keys"] = msg["keys"]
            entry["new_epoch"] = msg["new_epoch"]
            entry["staged"] = msg.get("staged", [])
        elif op == "resize_discard":
            entry["keys"] = msg["keys"]
        elif op == "resize_seal":
            entry["keys"] = msg["keys"]
            entry["new_epoch"] = msg["new_epoch"]
            entry["addresses"] = msg.get("addresses", [])
        else:  # command
            entry["head"] = msg["head"]
            entry["body"] = msg["body"]
        links = [l for l in self._followers.values() if l.alive]
        if not links:
            return None
        latch = _AckLatch(len(links), self._applied_seq) \
            if _repl_sync() else None
        for link in links:
            link.enqueue(entry, latch)
        return latch

    def _replicate_apply_locked(self, msg):
        """Follower side of the update stream: apply in log order, ack
        duplicates, request a resync on a gap, and fence primaries whose
        epoch is behind ours."""
        e = int(msg.get("epoch", 0))
        if e < self.epoch:
            return {"ok": False, "stale_epoch": True, "epoch": self.epoch,
                    "err": "replication from a deposed primary "
                           "(epoch %d < %d)" % (e, self.epoch)}
        if e > self.epoch:
            self.epoch = e
            if self.role == "primary":
                # a newer primary is streaming to us: it owns the shard
                self.role = "follower"
        if msg.get("rop") == "snapshot":
            self._install_snapshot_locked(msg)
            return {"ok": True, "snapshot": True, "rseq": self._applied_seq}
        rseq = int(msg["rseq"])
        if rseq <= self._applied_seq:
            return {"ok": True, "dup": True, "rseq": self._applied_seq}
        if rseq != self._applied_seq + 1:
            return {"ok": False, "resync": True, "rseq": self._applied_seq,
                    "err": "replication gap: have %d, got %d"
                           % (self._applied_seq, rseq)}
        orank = int(msg.get("orank", -1))
        resp = self._dispatch_locked(msg["rop"], orank, msg)
        if not resp.get("ok"):
            # local apply diverged from the primary's (e.g. optimizer not
            # installed yet): ask for a snapshot instead of silently
            # skipping the entry and forking the weights
            return {"ok": False, "resync": True, "rseq": self._applied_seq,
                    "err": "replica apply failed: %s" % resp.get("err")}
        self._applied_seq = rseq
        oseq = msg.get("oseq")
        if oseq is not None and msg["rop"] in _MUTATING_OPS:
            self._last_seq[orank] = (oseq, msg.get("resp", resp))
        return {"ok": True, "rseq": rseq}

    def _promote_locked(self, msg):
        e = int(msg.get("epoch", 0))
        if e > self.epoch:
            self.epoch = e
            self.role = "primary"
            return {"ok": True, "epoch": self.epoch,
                    "rseq": self._applied_seq}
        if e == self.epoch and self.role == "primary":
            # retried promote (client lost the first response): ack
            return {"ok": True, "epoch": self.epoch,
                    "rseq": self._applied_seq}
        return {"ok": False, "stale_epoch": True, "epoch": self.epoch,
                "err": "promote to epoch %d rejected (server epoch %d)"
                       % (e, self.epoch)}

    def _sync_follower_locked(self, msg):
        if self.role != "primary":
            return {"ok": False, "not_primary": True, "epoch": self.epoch,
                    "err": "sync_follower: server s%d is %s, not primary"
                           % (self.server_id, self.role)}
        addr = msg.get("addr")
        if not addr:
            return {"ok": False, "err": "sync_follower: missing addr"}
        # snapshot + registration are atomic under the server lock: every
        # mutation after this point flows through the new follower link
        snap = self._snapshot_locked()
        old = self._followers.pop(addr, None)
        if old is not None:
            old.close()
        self._followers[addr] = _FollowerLink(self, addr)
        resp = {"ok": True}
        resp.update(snap)
        return resp

    def _fence(self, new_epoch):
        """Demote a deposed primary: reject all client traffic from now
        on.  Called when a follower (or client) proves a newer epoch
        exists."""
        with self._lock:
            if new_epoch > self.epoch:
                self.epoch = new_epoch
            if self.role == "fenced":
                return
            _LOG.warning("AsyncServer s%d: fenced at epoch %d (a newer "
                         "primary owns this shard)", self.server_id,
                         self.epoch)
            self.role = "fenced"
            links = list(self._followers.values())
            self._followers = {}
        # outside the lock; the role guard above makes this exactly-once
        # per demotion no matter how many streams report the new epoch
        _M_FENCED.inc()
        _flight.record_failure("fenced", server_id=self.server_id,
                               address=self.address, epoch=self.epoch)
        for link in links:
            link.close()

    def _drop_follower(self, addr, link):
        with self._lock:
            if self._followers.get(addr) is link:
                del self._followers[addr]

    # -- message dispatch (runs on handler threads) --------------------
    def dispatch(self, msg):
        op = msg.get("op")
        # serve latency starts HERE: a chaos delay below (the slow-shard
        # injection) and the tail replication latch wait both belong to
        # what the worker experienced from this shard
        t_serve = time.monotonic()
        # the pusher's span context travels as an OPTIONAL header field;
        # a frame without one (old peer) or with a corrupt one attaches
        # nothing — tracing must never fail the RPC (attach_wire_context
        # swallows bad tokens)
        trace_tok = msg.pop("trace", None)
        try:
            _chaos.visit("kvstore.server_kill",
                         name="s%d:%s:%s" % (self.server_id, self.role, op))
        except Exception as exc:
            # a fired rule IS this server's crash: die abruptly (no
            # drain) and cut the caller mid-RPC so the client-side
            # retry/failover path — not a test-only path — runs
            self.kill()
            raise ConnectionResetError(
                "chaos: server s%d killed (op=%r)"
                % (self.server_id, op)) from exc
        with self._inflight_cv:
            self._inflight += 1
        try:
            with _tracing.attach_wire_context(trace_tok), \
                    _tracing.span("kv.serve.%s" % op, cat="kvstore",
                                  server=self.server_id, role=self.role):
                resp, latch = self._dispatch(msg)
        finally:
            with self._inflight_cv:
                self._inflight -= 1
                self._inflight_cv.notify_all()
        if latch is not None and not latch.wait(_repl_timeout_s()):
            # availability over strictness: answer the worker anyway; the
            # entry stays queued and still reaches the follower unless
            # the primary dies first (which sync mode exists to bound)
            _LOG.warning(
                "AsyncServer s%d: follower ack for entry rseq=%d timed out "
                "after %.1fs (replication lagging)", self.server_id,
                latch.rseq, _repl_timeout_s())
        if op not in ("heartbeat", "stats"):
            # probes would drown the data ops' signal in the skew series
            _M_SERVE.labels(str(op), str(self.server_id)).observe(
                time.monotonic() - t_serve)
        return resp

    def _dispatch(self, msg):
        op = msg["op"]
        rank = msg.get("rank", -1)
        seq = msg.get("seq")
        cep = msg.get("epoch")
        dedup = seq is not None and op in _MUTATING_OPS
        with self._lock:
            if rank >= 0:
                # negative ranks are internal (rejoin handshakes) and
                # must not pollute worker liveness accounting
                self._heartbeat[rank] = time.time()
            if op == "heartbeat":
                return {"ok": True, "epoch": self.epoch,
                        "role": self.role}, None
            if op == "stats":
                return self._stats_locked(), None
            if op == "shutdown":
                self._shutdown.set()
                return {"ok": True}, None
            if op == "replicate":
                return self._replicate_apply_locked(msg), None
            if op == "promote":
                return self._promote_locked(msg), None
            if op == "sync_follower":
                return self._sync_follower_locked(msg), None
            if self.role == "fenced":
                return {"ok": False, "stale_epoch": True,
                        "not_primary": True, "epoch": self.epoch,
                        "err": "server s%d fenced at epoch %d — a newer "
                               "primary owns this shard"
                               % (self.server_id, self.epoch)}, None
            if op == "pull":
                rej = self._moved_reject_locked(msg["keys"])
                if rej is not None:
                    return rej, None
                return self._pull_locked(msg), None
            if op == "resize_export":
                # read-only side of the re-striping protocol: primary-only
                # (followers may lag the seqnos a warm copy is staged
                # against), but deliberately NOT dedup'd or replicated
                if self.role != "primary":
                    return {"ok": False, "not_primary": True,
                            "epoch": self.epoch,
                            "err": "resize_export: server s%d is %s — "
                                   "exports come from the primary"
                                   % (self.server_id, self.role)}, None
                return self._resize_export_locked(msg), None
            if op == "snapshot_export":
                # read-only side of the consistent-cut protocol: same
                # contract as resize_export — primary-only (followers
                # may lag the seqno marks a cut is diffed against), and
                # deliberately NOT dedup'd or replicated
                if self.role != "primary":
                    return {"ok": False, "not_primary": True,
                            "epoch": self.epoch,
                            "err": "snapshot_export: server s%d is %s — "
                                   "snapshots cut from the primary"
                                   % (self.server_id, self.role)}, None
                return self._snapshot_export_locked(msg), None
            if op not in _REPLICATED_OPS:
                return {"ok": False, "err": "unknown op %r" % op}, None
            # mutating client ops: primary-only, epoch-fenced
            if self.role != "primary":
                return {"ok": False, "not_primary": True,
                        "epoch": self.epoch,
                        "err": "server s%d is a follower (epoch %d) — "
                               "mutations go to the primary"
                               % (self.server_id, self.epoch)}, None
            if cep is not None and cep < self.epoch:
                return {"ok": False, "stale_epoch": True,
                        "epoch": self.epoch,
                        "err": "stale client epoch %d < server epoch %d — "
                               "refresh membership and retry"
                               % (cep, self.epoch)}, None
            if dedup:
                last = self._last_seq.get(rank)
                if last is not None and last[0] == seq:
                    if op == "push_pull" and last[1].get("ok"):
                        # the cached entry is the bounded push-ack (a
                        # cached copy of the pulled weights per worker
                        # would defeat pull's no-retained-response
                        # design); the pull half is idempotent — re-run
                        # it fresh
                        return self._pull_locked({"keys": msg["keys"]}), \
                            None
                    return last[1], None  # duplicate of a completed request
            if op == "push_pull":
                # fused step RPC: apply the push half (same validation
                # and seqno bumps as a plain push), replicate it as a
                # plain push entry, then serve the pull half from the
                # just-updated store — one wire round trip per shard per
                # step instead of two
                rej = self._moved_reject_locked(
                    [k for k, _ in msg["pairs"]] + list(msg["keys"]))
                if rej is not None:
                    return rej, None
                resp = self._dispatch_locked("push", rank, msg)
                if dedup:
                    self._last_seq[rank] = (seq, resp)
                if not resp.get("ok"):
                    return resp, None
                latch = self._append_entry_locked("push", rank, seq, msg,
                                                  resp)
                return self._pull_locked({"keys": msg["keys"]}), latch
            if op in ("init", "push"):
                # AFTER dedup: a push applied before its key moved must
                # still answer its retry from cache (the applied update
                # travelled with the key), never re-route and re-apply
                rej = self._moved_reject_locked(
                    [k for k, _ in msg["pairs"]])
                if rej is not None:
                    return rej, None
            resp = self._dispatch_locked(op, rank, msg)
            if dedup:
                self._last_seq[rank] = (seq, resp)
            latch = None
            if resp.get("ok") and op in _REPLICATED_OPS:
                latch = self._append_entry_locked(op, rank, seq, msg, resp)
            return resp, latch

    def _pull_locked(self, msg):
        # copy under the lock: handlers serialize the response after
        # release, and push handlers mutate weights in place — a
        # live reference could serialize a torn (mid-update) tensor
        resp = {"ok": True, "epoch": self.epoch,
                "vals": [None if self._store.get(k) is None
                         else _np.array(self._store[k])
                         for k in msg["keys"]]}
        if msg.get("seqnos"):
            resp["seqnos"] = [int(self._seqnos.get(k, 0))
                              for k in msg["keys"]]
        return resp

    # -- elastic re-striping (``elastic.ResizePlan``) -------------------

    def _moved_reject_locked(self, keys):
        """Tombstone fence: None when no key has been re-striped away,
        else the typed moved/stale_epoch rejection carrying the cutover
        epoch so the caller refreshes topology rather than failing over."""
        hit = [k for k in keys if k in self._moved]
        if not hit:
            return None
        newest = max((self._moved[k] for k in hit),
                     key=lambda t: t["epoch"])
        resp = {"ok": False, "stale_epoch": True, "moved": True,
                "epoch": newest["epoch"],
                "err": "key(s) %s re-striped off server s%d at topology "
                       "epoch %d — refresh the elastic topology and retry"
                       % (", ".join(sorted(repr(k) for k in hit)),
                          self.server_id, newest["epoch"])}
        # a SEALED tombstone (cutover fully committed) forwards the new
        # shard list, so even a worker with no topology directory entry
        # can re-route; an unsealed one means the commit (or its abort)
        # is still in flight — the caller polls
        if newest.get("addresses"):
            resp["addresses"] = list(newest["addresses"])
        return resp

    def _opt_states_locked(self, keys):
        """Per-key optimizer slots (momentum etc.) for an export; {} when
        no optimizer is installed or no key has accumulated state yet."""
        if self._updater is None:
            return {}
        states = getattr(self._updater._updater, "states", {})
        out = {}
        for k in keys:
            sk = repr(k) if isinstance(k, tuple) else k
            if sk in states:
                out[sk] = states[sk]
        return out

    def _resize_export_locked(self, msg):
        """Warm-copy source: values + per-key seqnos (the staging marks
        that ``resize_retire`` later diffs against) + optimizer slots,
        HMAC-gated like every executable payload."""
        keys = msg["keys"]
        missing = [k for k in keys if k not in self._store]
        if missing:
            return {"ok": False,
                    "err": "resize_export: keys %r not on server s%d"
                           % (missing, self.server_id)}
        resp = {"ok": True, "epoch": self.epoch,
                "vals": [_np.array(self._store[k]) for k in keys],
                "seqlist": [[_wire_key(k), int(self._seqnos.get(k, 0))]
                            for k in keys]}
        states = self._opt_states_locked(keys)
        if states or self._opt_raw is not None:
            raw = pickle.dumps({"states": states,
                                "opt_raw": self._opt_raw})
            resp["optimizer"] = raw
            resp["mac"] = _optimizer_mac(self.secret, raw)
        return resp

    def _snapshot_export_locked(self, msg):
        """Consistent-cut source: a full (warm pass) or dirty-delta (cut
        pass, taken inside the group's frozen routing window) export of
        every key this primary owns.  ``since`` carries the warm pass's
        seqno marks as ``[[wire_key, seqno], ...]``: only keys whose
        seqno advanced past their mark ship values again, so the frozen
        window pays for the delta — never the full transfer, which
        happened warm.  ``seqlist`` always covers every live key (the
        cut's final marks, recorded into the snapshot).  Optimizer slots
        ride the same HMAC-gated pickle as every executable payload."""
        since = {_unwire_key(k): int(n)
                 for k, n in (msg.get("since") or [])}
        keys = sorted(self._store, key=repr)
        seqlist = [[_wire_key(k), int(self._seqnos.get(k, 0))]
                   for k in keys]
        dirty = [k for k in keys
                 if k not in since
                 or int(self._seqnos.get(k, 0)) > since[k]]
        resp = {"ok": True, "epoch": self.epoch,
                "server_id": self.server_id, "seqlist": seqlist,
                "pairs": [(k, _np.array(self._store[k])) for k in dirty]}
        states = self._opt_states_locked(dirty)
        if states or self._opt_raw is not None:
            raw = pickle.dumps({"states": states,
                                "opt_raw": self._opt_raw})
            resp["optimizer"] = raw
            resp["mac"] = _optimizer_mac(self.secret, raw)
        return resp

    def _stats_locked(self):
        now = time.time()
        dead = [r for r, t in self._heartbeat.items()
                if now - t > _dead_after_s()]
        return {"ok": True, "server_id": self.server_id,
                "role": self.role, "epoch": self.epoch,
                "applied_seq": self._applied_seq,
                "followers": [[a, l.acked_rseq]
                              for a, l in sorted(self._followers.items())],
                "push_counts": [[r, c] for r, c
                                in sorted(self._push_counts.items())],
                "dead": dead, "workers": sorted(self._heartbeat),
                "keys": sorted((repr(k) for k in self._store)),
                "moved": sorted((repr(k) for k in self._moved))}

    def _dispatch_locked(self, op, rank, msg):
        if op == "init":
            # first writer wins (matches reference init-once semantics)
            for k, v in msg["pairs"]:
                if k not in self._store:
                    self._store[k] = _np.array(v, copy=True)
                    self._seqnos[k] = self._seqnos.get(k, 0) + 1
            return {"ok": True}
        if op == "push":
            if self._updater is None:
                # the reference's async server runs the optimizer; a
                # raw-gradient += would be silent lr=-1 ascent
                return {"ok": False,
                        "err": "server optimizer not set — call "
                               "set_optimizer() before push"}
            # validate everything BEFORE mutating: a partial update
            # followed by a client retry would double-apply gradients
            bad = [k for k, _ in msg["pairs"] if k not in self._store]
            if bad:
                return {"ok": False, "err": "keys %r not init" % (bad,)}
            for k, g in msg["pairs"]:
                # update-on-push: no aggregation, no barrier
                self._updater(k, g, self._store[k])
                self._seqnos[k] = self._seqnos.get(k, 0) + 1
            self._push_counts[rank] = self._push_counts.get(rank, 0) + 1
            return {"ok": True}
        if op == "set_optimizer":
            raw = msg["optimizer"]
            mac = msg.get("mac", "")
            if not _hmaclib.compare_digest(
                    mac, _optimizer_mac(self.secret, raw)):
                return {"ok": False,
                        "err": "set_optimizer rejected: bad or missing "
                               "HMAC (the optimizer payload is the one "
                               "pickled message and requires the per-job "
                               "secret)"}
            from . import optimizer as opt

            optimizer = pickle.loads(raw)
            self._updater = _NumpyUpdater(opt.get_updater(optimizer))
            self._opt_raw = bytes(raw)
            return {"ok": True}
        if op == "command":
            # reference kController escape hatch: kept for inspection
            self._commands.append((msg["head"], msg["body"]))
            return {"ok": True}
        if op == "resize_install":
            return self._resize_install_locked(msg)
        if op == "resize_retire":
            return self._resize_retire_locked(msg)
        if op == "resize_discard":
            return self._resize_discard_locked(msg)
        if op == "resize_seal":
            return self._resize_seal_locked(msg)
        return {"ok": False, "err": "unknown op %r" % op}

    def _resize_seal_locked(self, msg):
        """Final step of a committed cutover: stamp the new shard list
        onto the tombstones so moved rejections become self-describing
        forwarding pointers (stragglers re-route without a directory)."""
        addresses = [str(a) for a in msg.get("addresses", [])]
        new_epoch = int(msg["new_epoch"])
        for k in msg["keys"]:
            t = self._moved.get(k)
            if t is not None and t["epoch"] <= new_epoch:
                t["epoch"] = new_epoch
                t["addresses"] = addresses
        return {"ok": True}

    def _resize_install_locked(self, msg):
        """Stage keys arriving from their old owner.  Seqno-guarded and
        idempotent: a retried install (or a stale warm copy racing the
        commit's dirty delta) never rolls a key backwards.  Installing a
        key clears any tombstone — the key is coming (back) home."""
        raw = msg.get("optimizer")
        states = {}
        if raw is not None:
            if not _hmaclib.compare_digest(
                    msg.get("mac", ""), _optimizer_mac(self.secret, raw)):
                return {"ok": False,
                        "err": "resize_install rejected: bad or missing "
                               "HMAC on the optimizer-state payload "
                               "(shards must share the per-job secret)"}
            states = pickle.loads(raw).get("states", {})
        seqmap = {_unwire_key(k): int(n) for k, n in msg.get("seqlist", [])}
        installed = []
        for k, v in msg["pairs"]:
            seq = seqmap.get(k, 1)
            if k in self._store and self._seqnos.get(k, 0) >= seq:
                self._moved.pop(k, None)
                continue
            self._store[k] = _np.array(v, copy=True)
            self._seqnos[k] = seq
            self._moved.pop(k, None)
            installed.append(k)
        if states and self._updater is not None:
            self._updater._updater.states.update(states)
        return {"ok": True, "installed": [_wire_key(k) for k in installed]}

    def _resize_retire_locked(self, msg):
        """Freeze + export + tombstone, atomically: delete the keys from
        this shard, leave ``moved`` tombstones at ``new_epoch``, and
        return — in the same response — the (value, seqno, optimizer
        slot) of every key that advanced past its staged seqno since the
        warm copy.  Idempotent: retiring an already-retired key only
        refreshes its tombstone."""
        new_epoch = int(msg["new_epoch"])
        staged = {_unwire_key(k): int(n) for k, n in msg.get("staged", [])}
        dirty_keys, dirty_pairs, dirty_seq = [], [], []
        for k in msg["keys"]:
            if k not in self._store:
                self._moved[k] = {"epoch": new_epoch}
                continue
            seqno = int(self._seqnos.get(k, 0))
            if seqno != staged.get(k):
                # pushes landed after the warm copy: the staged copy on
                # the new owner is stale for this key — ship the delta
                dirty_keys.append(k)
                dirty_pairs.append((k, _np.array(self._store[k])))
                dirty_seq.append([_wire_key(k), seqno])
            del self._store[k]
            self._seqnos.pop(k, None)
            self._moved[k] = {"epoch": new_epoch}
        states = self._opt_states_locked(dirty_keys)
        if self._updater is not None:
            upd_states = getattr(self._updater._updater, "states", {})
            for k in msg["keys"]:
                upd_states.pop(repr(k) if isinstance(k, tuple) else k, None)
        resp = {"ok": True, "epoch": self.epoch, "pairs": dirty_pairs,
                "seqlist": dirty_seq}
        if states:
            raw = pickle.dumps({"states": states})
            resp["optimizer"] = raw
            resp["mac"] = _optimizer_mac(self.secret, raw)
        return resp

    def _resize_discard_locked(self, msg):
        """Abort path: drop staged copies (and any tombstone — a rolled-
        back retire must leave the key servable at its old home)."""
        dropped = []
        for k in msg["keys"]:
            if k in self._store:
                del self._store[k]
                self._seqnos.pop(k, None)
                dropped.append(k)
            if self._updater is not None:
                getattr(self._updater._updater, "states", {}).pop(
                    repr(k) if isinstance(k, tuple) else k, None)
            self._moved.pop(k, None)
        return {"ok": True, "dropped": [_wire_key(k) for k in dropped]}


class _NumpyUpdater:
    """Adapts an mxnet updater (NDArray signature) to numpy server state."""

    def __init__(self, updater):
        self._updater = updater

    def __call__(self, key, grad, weight):
        from .ndarray import NDArray
        import jax.numpy as jnp

        # stripe chunks of one base key must keep distinct optimizer
        # state: the updater keys its state dict by this value
        state_key = repr(key) if isinstance(key, tuple) else key
        w = NDArray(jnp.asarray(weight))
        self._updater(state_key, NDArray(jnp.asarray(grad)), w)
        weight[...] = _np.asarray(w._data)


# internal ranks for rejoin handshakes: unique, negative (excluded from
# worker liveness accounting and from the per-worker dedup seq streams)
_rejoin_ranks = itertools.count(1)


class AsyncClient:
    """Worker-side connection to ONE async PS shard.

    A daemon thread heartbeats independently of application pushes (the
    ps-lite model), so liveness is not conflated with push frequency — a
    worker spending minutes in compute stays alive.  The heartbeat backs
    off exponentially after consecutive failures and EXITS once the
    server has been unreachable for the full death window (setting
    ``self.dead`` and firing ``on_dead``), instead of hammering a dead
    socket at a fixed interval forever.

    Recovery (parity: ps-lite resend + ``Postoffice::is_recovery``): a
    dropped connection is re-dialed transparently and the in-flight
    request retried with the SAME sequence number; the server's
    per-worker dedup returns the cached response if the first attempt
    actually completed, so gradients are applied at most once.

    Retry policy: exponential backoff with jitter (base 50 ms, cap 2 s),
    a per-attempt socket timeout (``call_timeout`` /
    ``MXNET_TPU_PS_CALL_TIMEOUT``), and an overall per-RPC deadline
    (``deadline`` / ``MXNET_TPU_PS_DEADLINE``) after which the server is
    declared dead with a typed :class:`ServerDeadError` — a worker never
    hangs forever on a shard that will not come back."""

    _BACKOFF_BASE_S = 0.05
    _BACKOFF_CAP_S = 2.0

    def __init__(self, address, rank, heartbeat=True, secret=None,
                 dial_timeout=60, call_timeout=None, deadline=None,
                 on_dead=None):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._rank = rank
        self._secret = secret or os.environ.get("MXNET_TPU_PS_SECRET")
        self._seq = 0
        # None defers to the env at CALL time (lazy, reconfigurable)
        self._call_timeout = call_timeout
        self._deadline = deadline
        self.dead = False
        self._closed = False
        self._on_dead = on_dead
        self._hb_stop = threading.Event()
        # backoff jitter: deterministic per rank so a test's retry
        # schedule replays, while distinct ranks still decorrelate
        self._backoff_rng = _random.Random(0x5EED ^ (rank & 0xFFFF))
        self._sock = self._dial(dial_timeout)
        self._lock = threading.Lock()
        if heartbeat:
            t = threading.Thread(target=self._heartbeat_loop,
                                 name="mxtpu-ps-heartbeat", daemon=True)
            t.start()

    def close(self):
        """Release the socket and stop the heartbeat thread.  Any call
        in flight (or made after) fails fast instead of retrying into a
        connection the owner has abandoned."""
        # single-transition fail-fast flag: taking self._lock here would
        # block close() behind an in-flight RPC, defeating its purpose
        self._closed = True  # graftcheck: disable=lock-discipline
        self._hb_stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _heartbeat_loop(self):
        failures = 0
        down_since = None
        last_ok = time.monotonic()
        hb_age = _M_HB_AGE.labels("%s:%d" % self._addr)
        while True:
            base = max(_heartbeat_interval_s(), 0.05)
            if failures:
                # exponential backoff against an unresponsive server —
                # probing a dead socket at the base rate buys nothing
                delay = min(base * (2 ** (failures - 1)),
                            max(_dead_after_s(), base))
            else:
                delay = base
            if self._hb_stop.wait(delay):
                return
            if self._closed or self.dead:
                return
            try:
                # short per-probe deadline: one probe must not eat the
                # whole death window in internal retries
                self._call({"op": "heartbeat"}, deadline=base)
            except Exception:  # noqa: BLE001 — any failure is a miss
                if self._closed:
                    return
                failures += 1
                now = time.monotonic()
                hb_age.set(now - last_ok)
                if down_since is None:
                    down_since = now
                if now - down_since >= _dead_after_s():
                    # declared dead: surface it and STOP probing.
                    # Monotone False->True flag with a single writer
                    # (this heartbeat thread); readers only poll it.
                    self.dead = True  # graftcheck: disable=lock-discipline
                    cb = self._on_dead
                    if cb is not None:
                        try:
                            cb(self)
                        except Exception:  # noqa: BLE001 — observer only
                            _LOG.exception("on_dead callback failed")
                    return
            else:
                failures = 0
                down_since = None
                last_ok = time.monotonic()
                hb_age.set(0.0)

    def _dial(self, timeout_s):
        """Connect with patience: launcher-spawned server processes may
        still be importing when the first worker dials."""
        deadline = time.time() + timeout_s
        while True:
            try:
                return socket.create_connection(
                    self._addr, timeout=self._effective_call_timeout())
            except (ConnectionError, OSError):
                if time.time() >= deadline:
                    raise
                time.sleep(0.3)

    def _effective_call_timeout(self):
        return (self._call_timeout if self._call_timeout is not None
                else _call_timeout_s())

    def _effective_deadline(self):
        return (self._deadline if self._deadline is not None
                else _deadline_s())

    def _reconnect_locked(self):
        # caller holds self._lock (the _call_impl retry loop)
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = socket.create_connection(
            self._addr, timeout=self._effective_call_timeout())

    def _backoff_sleep(self, attempt):
        """Exponential backoff with multiplicative jitter in [0.5, 1.5):
        retries from many workers against a recovering server spread out
        instead of arriving as a thundering herd."""
        base = min(self._BACKOFF_CAP_S,
                   self._BACKOFF_BASE_S * (2 ** attempt))
        return base * (0.5 + self._backoff_rng.random())

    def _call(self, msg, seq=None, deadline=None):
        """One at-most-once RPC.  ``seq`` lets an owner with a longer
        lifetime than this connection (``ReplicatedClient``) keep ONE
        monotonic per-worker stream across failovers, so a retry through
        a new primary still dedups; ``deadline`` overrides the overall
        retry budget (heartbeat probes use a short one).

        When tracing is on, the RPC runs inside a ``kv.rpc`` span whose
        context rides in the frame header's OPTIONAL ``trace`` field
        (old peers decode frames without it unchanged); the server
        re-attaches it so push/pull handling appears as this span's
        child in the merged trace."""
        if not _tracing.tracing_enabled():
            return self._call_impl(msg, seq, deadline)
        with _tracing.span("kv.rpc", cat="kvstore", op=msg.get("op"),
                           server="%s:%d" % self._addr) as sp:
            tok = _tracing.capture_wire_context()
            if tok is not None:
                msg["trace"] = tok
            _WIRE_TLS.bytes_out = _WIRE_TLS.bytes_in = 0
            _WIRE_TLS.encode_us = _WIRE_TLS.decode_us = 0.0
            resp = self._call_impl(msg, seq, deadline)
            # the _send_msg/_recv_msg seams dropped the frame sizes and
            # codec wall into the per-thread scratch under this span
            sp.set(bytes=int(_WIRE_TLS.bytes_out + _WIRE_TLS.bytes_in),
                   encode_us=round(_WIRE_TLS.encode_us, 1),
                   decode_us=round(_WIRE_TLS.decode_us, 1))
            return resp

    def _call_impl(self, msg, seq=None, deadline=None):
        msg["rank"] = self._rank
        t_rpc = time.monotonic()
        t_fail = None  # first transport failure — opens the retry window
        with self._lock:
            if seq is None:
                self._seq += 1
                seq = self._seq
            msg["seq"] = seq
            call_timeout = self._effective_call_timeout()
            overall = (deadline if deadline is not None
                       else self._effective_deadline())
            hard_deadline = time.monotonic() + overall
            attempt = 0
            while True:
                if self._closed:
                    raise ServerDeadError(
                        "async PS client for %s:%d is closed"
                        % self._addr)
                try:
                    if attempt:  # re-dial failures count as attempts too
                        self._reconnect_locked()
                    _chaos.visit("kvstore.call", name=msg.get("op"))
                    self._sock.settimeout(call_timeout)
                    _send_msg(self._sock, msg)
                    resp = _recv_msg(self._sock, op=msg.get("op"))
                    break
                except _MessageTooBig:
                    raise  # deterministic; retrying resends the same bytes
                except ValueError:
                    # corrupt/oversize frame from the peer: the socket may
                    # be desynchronized mid-payload — never reuse it
                    self._reconnect_locked()
                    raise
                except (EOFError, ConnectionError, socket.timeout,
                        OSError) as exc:
                    attempt += 1
                    if t_fail is None:
                        t_fail = time.monotonic()
                    pause = self._backoff_sleep(attempt - 1)
                    if time.monotonic() + pause >= hard_deadline:
                        if msg.get("op") not in _RETRY_UNACCOUNTED_OPS:
                            _M_RETRY_S.inc(time.monotonic() - t_fail)
                        raise ServerDeadError(
                            "async PS %s:%d unreachable after %d "
                            "attempt(s) within the %.1fs deadline "
                            "(op=%r, last error: %r) — set "
                            "MXNET_TPU_PS_DEADLINE to wait longer"
                            % (self._addr[0], self._addr[1], attempt,
                               overall, msg.get("op"), exc)) from exc
                    time.sleep(pause)
                    # retry (same seq: the server dedups completed requests)
        if t_fail is not None and msg.get("op") not in _RETRY_UNACCOUNTED_OPS:
            _M_RETRY_S.inc(time.monotonic() - t_fail)
        _M_RPC.labels(msg.get("op", "?")).observe(time.monotonic() - t_rpc)
        if not resp.get("ok"):
            if resp.get("stale_epoch") or resp.get("not_primary"):
                raise StaleEpochError(
                    "async kvstore: %s" % resp.get("err"),
                    epoch=resp.get("epoch"),
                    not_primary=bool(resp.get("not_primary")),
                    moved=bool(resp.get("moved")),
                    addresses=resp.get("addresses"))
            raise MXNetError("async kvstore: %s" % resp.get("err"))
        return resp

    def init(self, pairs):
        self._call({"op": "init", "pairs": pairs})

    def push(self, pairs):
        self._call({"op": "push", "pairs": pairs})

    def pull(self, keys):
        return self._call({"op": "pull", "keys": keys})["vals"]

    def push_pull(self, pairs, keys):
        """Fused push+pull: one round trip applies the gradients and
        returns the fresh weights (RPC coalescing, PR 17)."""
        return self._call({"op": "push_pull", "pairs": pairs,
                           "keys": keys})["vals"]

    def set_optimizer(self, pickled):
        if not self._secret:
            raise MXNetError(
                "set_optimizer needs the per-job PS secret (launcher env "
                "MXNET_TPU_PS_SECRET or coordination-KV discovery)")
        self._call({"op": "set_optimizer", "optimizer": pickled,
                    "mac": _optimizer_mac(self._secret, pickled)})

    def command(self, head, body):
        self._call({"op": "command", "head": head, "body": body})

    def shutdown(self):
        self._call({"op": "shutdown"})

    def stats(self):
        resp = self._call({"op": "stats"})
        resp["push_counts"] = {r: c for r, c in resp.get("push_counts", [])}
        return resp


# -- replica-group membership -------------------------------------------
#
# The directory maps a replica group (identified by its ORIGINAL address
# set, which every worker was configured with) to the current epoch,
# primary, and replica list.  It is process-local state guarded by one
# lock — exactly right for the in-process thread-backed layout the
# forced-CPU tier-1 uses (workers and servers share the process); for
# cross-process jobs the epoch also rides in the coordination-KV address
# record (``publish_address(epoch=)``) so late workers start from the
# promoted view.

_DIR_LOCK = threading.Lock()
_DIRECTORY = {}  # group key (sorted addr tuple) -> {epoch, replicas, primary}


def reset_membership():
    """Forget every replica-group membership record (test isolation)."""
    with _DIR_LOCK:
        _DIRECTORY.clear()


def _membership_key(addresses):
    return tuple(sorted(addresses))


def _membership_lookup(group):
    with _DIR_LOCK:
        rec = _DIRECTORY.get(group)
        if rec is None:
            return None
        return {"epoch": rec["epoch"], "replicas": list(rec["replicas"]),
                "primary": rec["primary"]}


def _membership_publish(group, epoch, replicas, primary):
    """Record a (possibly promoted) view; replica lists merge so rejoined
    servers stay visible to every worker.  Older epochs never overwrite
    newer ones — publishing is monotonic."""
    with _DIR_LOCK:
        rec = _DIRECTORY.get(group)
        if rec is not None and epoch < rec["epoch"]:
            return
        merged = list(dict.fromkeys(
            (rec["replicas"] if rec else []) + list(replicas)))
        _DIRECTORY[group] = {"epoch": int(epoch), "replicas": merged,
                             "primary": primary}


def _membership_note_replica(member_addr, new_addr):
    """A server rejoined under ``member_addr``'s primary: append its
    (new) address to every group record that contains the primary, so
    workers can fail over to it later."""
    with _DIR_LOCK:
        for rec in _DIRECTORY.values():
            if member_addr in rec["replicas"] \
                    and new_addr not in rec["replicas"]:
                rec["replicas"].append(new_addr)


class ReplicatedClient:
    """Worker-side routing for ONE logical shard backed by a replica
    group.  Presents the same surface as :class:`AsyncClient`, but:

    * requests go to the group's current **primary**, stamped with the
      worker's membership epoch (stale views get a typed reject and a
      refresh, never a silent apply on a zombie);
    * the logical per-worker sequence stream is owned HERE, not by the
      per-connection client, so an RPC retried across a failover keeps
      its seq and the (replicated) server-side dedup still applies it
      at most once;
    * on a dead primary (heartbeat verdict or exhausted RPC retries) it
      refreshes the membership view — another worker may have already
      promoted — else promotes the first reachable follower at
      ``epoch+1`` and retries the in-flight request; only a whole-group
      loss surfaces, as :class:`ServerDeadError`."""

    def __init__(self, addresses, rank, heartbeat=True, secret=None,
                 dial_timeout=60):
        addrs = [a.strip() for a in addresses if a and a.strip()]
        if not addrs:
            raise ValueError("ReplicatedClient needs at least one address")
        self._group = _membership_key(addrs)
        self._rank = rank
        self._secret = secret or os.environ.get("MXNET_TPU_PS_SECRET")
        self._hb = heartbeat
        self._dial_timeout = dial_timeout
        self._seq = 0
        self._lock = threading.RLock()
        self._dead_flag = False
        rec = _membership_lookup(self._group)
        if rec is None:
            _membership_publish(self._group, 0, addrs, addrs[0])
            rec = _membership_lookup(self._group)
        self.epoch = rec["epoch"]
        self._replicas = list(rec["replicas"])
        self._primary = rec["primary"]
        self._cli = self._connect(self._primary)

    @property
    def _addr(self):
        """(host, port) of the current primary — label parity with
        :class:`AsyncClient` for ``ServerGroup`` diagnostics."""
        host, port = self._primary.rsplit(":", 1)
        return (host, int(port))

    def close(self):
        self._cli.close()

    def _connect(self, addr):
        return AsyncClient(addr, self._rank, heartbeat=self._hb,
                           secret=self._secret,
                           dial_timeout=self._dial_timeout,
                           on_dead=self._note_primary_dead)

    def _note_primary_dead(self, _cli):
        # heartbeat thread context: flag only; the next call (under the
        # client lock) runs the failover
        self._dead_flag = True

    def _adopt(self, rec):
        """Switch to the directory's view of the group."""
        self.epoch = rec["epoch"]
        self._replicas = list(rec["replicas"])
        if rec["primary"] != self._primary:
            old = self._cli
            self._primary = rec["primary"]
            self._cli = self._connect(self._primary)
            self._dead_flag = False
            old.close()

    def _refresh_membership(self):
        """Adopt any newer membership view; True if it changed routing."""
        rec = _membership_lookup(self._group)
        if rec is None:
            return False
        changed = (rec["epoch"] > self.epoch
                   or rec["primary"] != self._primary)
        self._replicas = list(dict.fromkeys(
            self._replicas + list(rec["replicas"])))
        if changed:
            self._adopt(rec)
        return changed

    def _failover(self, last_exc=None):
        """Route around a dead primary: adopt a newer published view if
        one exists, else promote the first reachable standby at
        ``epoch+1`` and publish the new view."""
        t0 = time.monotonic()
        try:
            return self._failover_impl(last_exc)
        finally:
            _M_FAILOVER_S.inc(time.monotonic() - t0)

    def _failover_impl(self, last_exc=None):
        if self._refresh_membership():
            return
        target_epoch = self.epoch + 1
        for addr in [a for a in self._replicas if a != self._primary]:
            try:
                cand = AsyncClient(addr, self._rank, heartbeat=False,
                                   secret=self._secret, dial_timeout=0)
            except (ConnectionError, OSError):
                continue
            try:
                resp = cand._call({"op": "promote", "epoch": target_epoch},
                                  seq=self._next_seq(),
                                  deadline=_call_timeout_s())
            except StaleEpochError:
                # that replica already outranks our view: re-read the
                # directory (the promoter published) and try again
                cand.close()
                if self._refresh_membership():
                    return
                continue
            except (ServerDeadError, MXNetError, ConnectionError,
                    OSError) as exc:
                cand.close()
                last_exc = exc
                continue
            cand.close()
            old = self._cli
            self.epoch = int(resp.get("epoch", target_epoch))
            self._primary = addr
            self._cli = self._connect(addr)
            self._dead_flag = False
            _membership_publish(self._group, self.epoch, self._replicas,
                                addr)
            old.close()
            _M_FAILOVER.inc()
            _LOG.warning(
                "ReplicatedClient rank %d: failed over shard group %s to "
                "%s at epoch %d", self._rank, ",".join(self._group), addr,
                self.epoch)
            return
        exc = ServerDeadError(
            "replica group [%s]: no reachable standby to promote past "
            "epoch %d%s" % (",".join(self._replicas), self.epoch,
                            " — last error: %r" % (last_exc,)
                            if last_exc else ""))
        exc.__cause__ = last_exc
        _flight.record_failure("replica_group_lost", exc,
                               group=",".join(self._group),
                               epoch=self.epoch, rank=self._rank)
        raise exc

    def _next_seq(self):
        self._seq += 1
        return self._seq

    def _call(self, msg):
        with self._lock:
            seq = self._next_seq()
            failovers = 0
            cap = max(2 * len(self._replicas), 4)
            last = None
            while True:
                if self._dead_flag:
                    self._dead_flag = False
                    self._failover(last)
                try:
                    m = dict(msg)
                    m["epoch"] = self.epoch
                    return self._cli._call(m, seq=seq)
                except ServerDeadError as exc:
                    last = exc
                    failovers += 1
                    if failovers > cap:
                        _flight.record_failure(
                            "replica_group_lost", exc,
                            group=",".join(self._group),
                            epoch=self.epoch, rank=self._rank,
                            failovers=failovers)
                        raise
                    self._failover(exc)
                except StaleEpochError as exc:
                    if exc.moved:
                        # the KEY moved (elastic re-striping), not the
                        # primary: failing over inside the group cannot
                        # help — surface it so ServerGroup refreshes the
                        # key→shard topology instead
                        raise
                    last = exc
                    failovers += 1
                    if failovers > cap:
                        raise ServerDeadError(
                            "replica group [%s]: still fenced after %d "
                            "failover attempt(s): %s"
                            % (",".join(self._replicas), failovers,
                               exc)) from exc
                    if not self._refresh_membership():
                        self._failover(exc)

    def init(self, pairs):
        self._call({"op": "init", "pairs": pairs})

    def push(self, pairs):
        self._call({"op": "push", "pairs": pairs})

    def pull(self, keys, seqnos=False):
        resp = self._call({"op": "pull", "keys": keys, "seqnos": seqnos})
        if seqnos:
            return resp["vals"], resp.get("seqnos")
        return resp["vals"]

    def push_pull(self, pairs, keys):
        """Fused push+pull through the current primary; a failover
        retry keeps the seq, and the replicated dedup answers the push
        half from cache while re-running the idempotent pull half."""
        return self._call({"op": "push_pull", "pairs": pairs,
                           "keys": keys})["vals"]

    def set_optimizer(self, pickled):
        if not self._secret:
            raise MXNetError(
                "set_optimizer needs the per-job PS secret (launcher env "
                "MXNET_TPU_PS_SECRET or coordination-KV discovery)")
        self._call({"op": "set_optimizer", "optimizer": pickled,
                    "mac": _optimizer_mac(self._secret, pickled)})

    def command(self, head, body):
        self._call({"op": "command", "head": head, "body": body})

    def shutdown(self):
        self._call({"op": "shutdown"})

    def stats(self):
        resp = self._call({"op": "stats"})
        resp["push_counts"] = {r: c for r, c in resp.get("push_counts", [])}
        return resp


class ServerGroup:
    """Worker-side router over N PS shards (parity: the multi-server key
    layout of ``kvstore_dist.h:269-300``).

    * normal keys → one server by stable hash (``EncodeKey`` analog);
    * arrays with ``size >= bigarray_bound`` → striped into N contiguous
      flat chunks, chunk *i* on server *i* (``bigarray_bound_`` analog,
      env ``MXNET_KVSTORE_BIGARRAY_BOUND``, default 1e6 elements);
    * presents the same init/push/pull/stats surface as one client.

    Each shard address may be a replica GROUP — ``"host:p|host:q"`` (or
    a list of addresses): traffic then routes through a
    :class:`ReplicatedClient`, and the routing above (hash + striping)
    is over *logical* shards, so keys keep their placement across a
    failover inside any group."""

    def __init__(self, addresses, rank, heartbeat=True, secret=None,
                 bigarray_bound=None):
        self._rank = rank
        self._hb = heartbeat
        self._secret = secret
        self._specs = [self._normalize_spec(a) for a in addresses]
        self._clients = [self._build_client(sp) for sp in self._specs]
        self._n = len(self._clients)
        # elastic identity + routing state: the ORIGINAL spec list names
        # this group in the elastic topology directory forever (resizes
        # change _specs/_clients, never group_id); all routing reads/
        # writes happen under _route_lock so a cutover is atomic with
        # respect to in-flight group ops
        self.group_id = tuple(self._specs)
        self.topology_epoch = 0
        self._route_lock = threading.RLock()
        # NOTE: the bound decides routing, so it must agree across all
        # worker processes (the launcher exports one env for the job) —
        # exactly the reference's bigarray_bound_ contract
        self._bound = int(bigarray_bound if bigarray_bound is not None
                          else os.environ.get("MXNET_KVSTORE_BIGARRAY_BOUND",
                                              "1000000"))
        self._striped = {}  # base key -> (shape, n_chunks)
        self._pool = None  # lazy persistent fan-out pool (hot path)
        # opt-in gradient compression (MXNET_TPU_KV_COMPRESS): per-key
        # eligibility is negotiated at init time via negotiate()
        self._compressor = _wire.GradCompressor.from_env()

    @staticmethod
    def _normalize_spec(a):
        """Canonical ``"addr|addr"`` string for one logical shard."""
        reps = a.split("|") if isinstance(a, str) else list(a)
        return "|".join(r.strip() for r in reps if r and r.strip())

    def _build_client(self, spec):
        reps = spec.split("|")
        if len(reps) > 1:
            return ReplicatedClient(reps, self._rank, heartbeat=self._hb,
                                    secret=self._secret)
        return AsyncClient(reps[0], self._rank, heartbeat=self._hb,
                           secret=self._secret)

    # -- elastic topology (``elastic.ResizePlan`` cutover target) -------

    def adopt_topology(self, addresses, epoch):
        """Atomically cut key→shard routing over to an epoch-bumped
        shard list.  Clients for surviving shard specs are reused (their
        sockets, seq streams and dedup state stay valid); removed
        shards' clients are closed; striped keys are re-chunked to the
        new shard count.  Idempotent and monotonic: an older or equal
        epoch with the same specs is a no-op."""
        specs = [self._normalize_spec(a) for a in addresses]
        if not specs:
            raise ValueError("adopt_topology: empty shard list")
        with self._route_lock:
            if int(epoch) <= self.topology_epoch and specs == self._specs:
                return
            old = dict(zip(self._specs, self._clients))
            clients, reused = [], set()
            for sp in specs:
                if sp in old and sp not in reused:
                    clients.append(old[sp])
                    reused.add(sp)
                else:
                    clients.append(self._build_client(sp))
            for sp, cli in old.items():
                if sp not in reused:
                    cli.close()
            self._clients = clients
            self._specs = specs
            self._n = len(clients)
            if self._n > 1:
                self._striped = {k: (shape, self._n)
                                 for k, (shape, _) in self._striped.items()}
            else:
                # a single shard holds whole tensors under the plain key
                self._striped = {}
            self.topology_epoch = max(self.topology_epoch, int(epoch))
            if self._pool is not None:
                # pool width tracks shard count; no jobs are in flight
                # (ops run under _route_lock)
                self._pool.shutdown(wait=False)
                self._pool = None

    def routing_frozen(self):
        """The routing lock, usable as a context manager: while held, no
        group op runs.  ``elastic.ResizePlan`` holds it across its
        commit critical section so same-process ops never observe the
        mid-cutover state (retired-but-unsealed keys)."""
        return self._route_lock

    def _refresh_topology(self):
        """Adopt a newer published topology for this group; True if
        routing changed.  (Lazy import: elastic imports this module.)"""
        from . import elastic as _elastic

        rec = _elastic.lookup_topology(self.group_id)
        if rec is None or rec["epoch"] <= self.topology_epoch:
            return False
        self.adopt_topology(rec["addresses"], rec["epoch"])
        return True

    @staticmethod
    def _moved_cause(exc):
        """The moved-key StaleEpochError behind this failure (possibly
        wrapped in a ShardFailedError), or None."""
        node, seen = exc, set()
        while node is not None and id(node) not in seen:
            if isinstance(node, StaleEpochError) \
                    and getattr(node, "moved", False):
                return node
            seen.add(id(node))
            node = node.__cause__ if node.__cause__ is not None \
                else node.__context__
        return None

    def _routed(self, fn):
        """Run one group op under the routing lock.  A moved-key reject
        (a straggler op raced a re-striping cutover) means the key→shard
        assignment changed under us:

        * a SEALED rejection forwards the new shard list — adopt it and
          retry against the new routing;
        * otherwise consult the elastic topology directory;
        * a rejection with neither (the cutover — or its abort — is
          still committing) is polled: retrying against the OLD home
          succeeds the moment an abort clears the tombstone, and picks
          up the forwarding pointer the moment the commit seals.  The
          poll is bounded by ``MXNET_TPU_RESIZE_STALL_S`` so a wedged
          cutover surfaces the typed error instead of hanging forever.

        Note moved rejections happen BEFORE any server-side apply, so
        retrying the whole fan-out cannot double-apply on the rejecting
        shard; in-process resizes additionally hold this routing lock
        across the whole commit, so same-process ops never observe the
        mid-cutover state at all."""
        with self._route_lock:
            deadline = None
            while True:
                try:
                    return fn()
                except (StaleEpochError, ShardFailedError) as exc:
                    mv = self._moved_cause(exc)
                    if mv is None:
                        raise
                    if mv.addresses:
                        self.adopt_topology(mv.addresses, mv.epoch or 0)
                        continue
                    if self._refresh_topology():
                        continue
                    if deadline is None:
                        deadline = time.monotonic() + float(os.environ.get(
                            "MXNET_TPU_RESIZE_STALL_S", "30"))
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.025)

    def _shard_label(self, server):
        try:
            host, port = self._clients[server]._addr
            return "shard %d (%s:%d)" % (server, host, port)
        except Exception:  # noqa: BLE001 — labels are best-effort
            return "shard %d" % server

    def _fanout(self, jobs):
        """Run shard requests CONCURRENTLY (each client has its own
        socket+lock); one blocking RTT per server in sequence would make
        PS latency grow linearly with -s N.  ``jobs`` is a list of
        ``(server_index, thunk)``; returns thunk results in order.  The
        pool is persistent: push/pull run per training step.

        Error surfacing: every shard's outcome is collected (no
        fail-on-first-``result()``, which would leave later shards'
        errors unobserved), then one :class:`ShardFailedError` names
        each failing shard by index AND address, chained to the first
        underlying exception — a multi-server outage is attributable
        instead of an anonymous hang or a bare socket error.  For a
        replicated shard, reaching this point means the WHOLE group is
        gone (``ReplicatedClient`` absorbs single-replica deaths)."""
        if len(jobs) == 1:
            server, thunk = jobs[0]
            try:
                return [thunk()]
            except (ServerDeadError, ConnectionError, OSError,
                    EOFError) as exc:
                err = ShardFailedError(
                    "async PS fan-out failed at %s: %r"
                    % (self._shard_label(server), exc))
                err.__cause__ = exc
                _flight.record_failure("shard_failed", err,
                                       shards=self._shard_label(server),
                                       rank=self._rank)
                raise err from exc
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=self._n, thread_name_prefix="mxtpu-ps-fanout")
        futures = [(server, self._pool.submit(thunk))
                   for server, thunk in jobs]
        results, failures = [], []
        for server, fut in futures:
            try:
                results.append(fut.result())
            except Exception as exc:  # noqa: BLE001 — per-shard triage
                results.append(None)
                failures.append((server, exc))
        if failures:
            err = ShardFailedError(
                "async PS fan-out failed on %d/%d shard(s): %s"
                % (len(failures), len(jobs),
                   "; ".join("%s: %r" % (self._shard_label(s), e)
                             for s, e in failures)))
            err.__cause__ = failures[0][1]
            _flight.record_failure(
                "shard_failed", err, rank=self._rank,
                shards="; ".join(self._shard_label(s)
                                 for s, _ in failures))
            raise err from failures[0][1]
        return results

    @property
    def num_servers(self):
        return self._n

    def server_of(self, key):
        """Stable shard assignment for a non-striped key."""
        return zlib.crc32(repr(key).encode("utf-8")) % self._n

    def _split(self, key, arr):
        """[(server, wire_key, chunk), ...] for one (key, value) pair."""
        arr = _np.asarray(arr)
        if self._n > 1 and arr.size >= self._bound:
            self._striped[key] = (arr.shape, self._n)
            chunks = _np.array_split(arr.ravel(), self._n)
            return [(i, ("stripe", key, i), c)
                    for i, c in enumerate(chunks)]
        return [(self.server_of(key), key, arr)]

    def _scatter(self, pairs):
        per_server = {}
        for key, value in pairs:
            for server, wire_key, chunk in self._split(key, value):
                per_server.setdefault(server, []).append((wire_key, chunk))
        return per_server

    def init(self, pairs):
        """Cross-server atomic init.

        Only rank 0 writes initial values (parity: ``kvstore_dist.h``
        ``Init`` — rank-0 ``Push_`` then ``Barrier()``); every other
        rank BLOCKS until rank 0's init is visible on all the shards it
        touches.  Per-shard first-writer-wins alone is not atomic
        across servers: with N workers racing, shard A could keep
        worker 0's value while shard B keeps worker 1's — for a striped
        big array that is a torn initial tensor.

        As in the reference, the VALUES passed on ranks != 0 are
        ignored by contract (only shapes drive stripe routing); a key
        rank 0 never initializes times out with a clear error rather
        than committing another rank's value.
        """
        comp = self._compressor
        if comp is not None:
            # negotiation point: every rank admits the same wire keys
            # (striping is a pure function of shape + the job-wide
            # bound), so a compressed push from any worker is one the
            # server knows how to decompress — self-describing frames
            # make that a local decision, not a handshake
            for key, value in pairs:
                for _s, wk, chunk in self._split(key, value):
                    comp.negotiate(wk, chunk)
        if self._rank != 0:
            self.wait_for_init([(k, _np.asarray(v).shape)
                                for k, v in pairs])
            return
        self._routed(lambda: self._fanout(
            [(s, lambda s=s, p=p: self._clients[s].init(p))
             for s, p in self._scatter(pairs).items()]))

    def wait_for_init(self, key_shapes, timeout=None):
        """Block until every key is initialized on its shard(s);
        the init-barrier half of the reference's rank-0+Barrier
        contract.  Shapes drive stripe routing (same pure function of
        element count the initializing rank used)."""
        timeout = float(timeout if timeout is not None else
                        os.environ.get("MXNET_TPU_PS_INIT_TIMEOUT", "120"))
        pending = list(key_shapes)
        deadline = time.monotonic() + timeout
        delay = 0.02
        while True:
            # only still-missing keys are re-pulled: existence is the
            # question, and re-fetching already-initialized big striped
            # tensors every poll would multiply startup traffic
            keys = [k for k, _ in pending]
            shapes = [s for _, s in pending]
            vals = self.pull(keys, shapes=shapes)
            pending = [ks for ks, v in zip(pending, vals) if v is None]
            if not pending:
                return
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    "dist_async init barrier: keys %r not initialized "
                    "by rank 0 within %.0fs"
                    % ([k for k, _ in pending], timeout))
            time.sleep(delay)
            delay = min(delay * 2, 0.5)

    def _maybe_compress(self, per_server):
        """Run push gradients through the negotiated compressor (binary
        wire only — the JSON frame has no compressed-tensor form)."""
        comp = self._compressor
        if comp is None or _wire.wire_format() != "binary":
            return per_server
        return {s: [(k, comp.compress(k, v)) for k, v in p]
                for s, p in per_server.items()}

    def push(self, pairs):
        def go():
            per = self._maybe_compress(self._scatter(pairs))
            # one logical flush → len(per) wire RPCs (re-observed on a
            # topology-refresh retry, which really does fan out again)
            _M_WIRE_RPCS.observe(float(len(per)))
            return self._fanout(
                [(s, lambda s=s, p=p: self._clients[s].push(p))
                 for s, p in per.items()])
        self._routed(go)

    def pull(self, keys, shapes=None):
        return self._routed(lambda: self._pull_impl(keys, shapes))

    def push_pull(self, pairs, keys, shapes=None):
        """Fused flush: push ``pairs`` and pull ``keys`` in ONE wire RPC
        per shard (the server applies the update, then answers with the
        fresh weights).  With coalescing off the two logical flushes run
        as the classic two round trips."""
        if not _coalesce_enabled():
            self.push(pairs)
            return self.pull(keys, shapes)
        return self._routed(
            lambda: self._push_pull_impl(pairs, keys, shapes))

    def _push_pull_impl(self, pairs, keys, shapes):
        per = self._maybe_compress(self._scatter(pairs))
        requests, slots = self._pull_plan(keys, shapes)
        servers = sorted(set(per) | set(requests))
        # two logical flushes share len(servers) wire RPCs: book the
        # amortized width once per flush, and the fan-out the fusion
        # avoided into the savings counter
        _M_WIRE_RPCS.observe(len(servers) / 2.0)
        _M_WIRE_RPCS.observe(len(servers) / 2.0)
        _M_COALESCE_SAVED.inc(
            float(len(per) + len(requests) - len(servers)))

        def job(s):
            if s in per and s in requests:
                return self._clients[s].push_pull(per[s], requests[s])
            if s in per:
                return self._clients[s].push(per[s])
            return self._clients[s].pull(requests[s])

        resp_list = self._fanout(
            [(s, lambda s=s: job(s)) for s in servers])
        responses = {s: r for s, r in zip(servers, resp_list)
                     if s in requests}
        return self._pull_gather(slots, responses)

    def _pull_plan(self, keys, shapes):
        """Route a pull: striped keys fan out to all servers; plain keys
        to one.  ``shapes`` (per-key tuples, e.g. the out buffers'
        shapes) makes routing deterministic for keys this worker never
        initialized itself: striping is a pure function of element count
        and the job-wide bound, so a pull-only worker computes the same
        layout the initializing worker did."""
        requests = {}  # server -> [wire keys]
        slots = []     # per key: ("plain", server, idx) | ("striped", [...])
        for pos, key in enumerate(keys):
            striped = key in self._striped
            if not striped and shapes is not None and self._n > 1:
                count = 1
                for d in shapes[pos]:
                    count *= int(d)
                if count >= self._bound:
                    self._striped[key] = (tuple(shapes[pos]), self._n)
                    striped = True
            if striped:
                parts = []
                for i in range(self._striped[key][1]):
                    wire = ("stripe", key, i)
                    requests.setdefault(i, [])
                    parts.append((i, len(requests[i])))
                    requests[i].append(wire)
                slots.append(("striped", key, parts))
            else:
                server = self.server_of(key)
                requests.setdefault(server, [])
                slots.append(("plain", server, len(requests[server])))
                requests[server].append(key)
        return requests, slots

    def _pull_gather(self, slots, responses):
        out = []
        for slot in slots:
            if slot[0] == "plain":
                _, server, idx = slot
                out.append(responses[server][idx])
            else:
                _, key, parts = slot
                chunks = [responses[s][i] for s, i in parts]
                if any(c is None for c in chunks):
                    out.append(None)
                else:
                    shape = self._striped[key][0]
                    out.append(_np.concatenate(chunks).reshape(shape))
        return out

    def _pull_impl(self, keys, shapes=None):
        requests, slots = self._pull_plan(keys, shapes)
        ordered = sorted(requests)
        _M_WIRE_RPCS.observe(float(len(ordered)))
        resp_list = self._fanout(
            [(s, lambda s=s: self._clients[s].pull(requests[s]))
             for s in ordered])
        responses = dict(zip(ordered, resp_list))
        return self._pull_gather(slots, responses)

    def set_optimizer(self, pickled):
        self._routed(lambda: self._fanout(
            [(i, lambda c=c: c.set_optimizer(pickled))
             for i, c in enumerate(self._clients)]))

    def command(self, head, body):
        self._routed(lambda: self._fanout(
            [(i, lambda c=c: c.command(head, body))
             for i, c in enumerate(self._clients)]))

    def shutdown(self):
        self._routed(lambda: self._fanout(
            [(i, lambda c=c: c.shutdown())
             for i, c in enumerate(self._clients)]))

    def stats(self):
        """Aggregate across shards; ``per_server`` keeps the raw shard
        stats (key placement, replica role/epoch etc.) observable."""
        per_server = self._routed(lambda: self._fanout(
            [(i, lambda c=c: c.stats())
             for i, c in enumerate(self._clients)]))
        push_counts = {}
        dead, workers = set(), set()
        for s in per_server:
            for r, c in s["push_counts"].items():
                push_counts[r] = push_counts.get(r, 0) + c
            dead.update(s.get("dead", []))
            workers.update(s.get("workers", []))
        return {"ok": True, "push_counts": push_counts,
                "dead": sorted(dead), "workers": sorted(workers),
                "per_server": per_server}


# -- address discovery over the jax.distributed coordination KV ---------

def publish_address(address, secret=None, epoch=0, metrics_port=None):
    """Publish the server address record.  ``address`` may be a full
    shard list (comma-separated) where each shard is a ``|``-separated
    replica group; ``epoch`` stamps the membership view so late-joining
    workers start from the promoted topology, not the original one;
    ``metrics_port`` (when the server also runs a ``/metrics``
    endpoint) travels with the record so a federation collector can
    find every shard's exposition — old readers ignore the extra key
    (``lookup_address`` only picks the fields it knows)."""
    from jax._src import distributed

    client = distributed.global_state.client
    if client is not None:
        rec = {"addr": address, "secret": secret, "epoch": int(epoch)}
        if metrics_port is not None:
            rec["metrics_port"] = int(metrics_port)
        client.key_value_set(_KV_KEY, _json.dumps(rec))


def lookup_address(timeout_s=60):
    """Returns (address, secret) — secret may be None (env-provided
    addresses carry no secret; MXNET_TPU_PS_SECRET supplies it)."""
    env = os.environ.get("MXNET_TPU_ASYNC_PS_ADDR")
    if env:
        return env, os.environ.get("MXNET_TPU_PS_SECRET")
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        return None, None
    record = client.blocking_key_value_get(_KV_KEY, int(timeout_s * 1000))
    try:
        parsed = _json.loads(record)
        return parsed["addr"], parsed.get("secret")
    except (ValueError, KeyError, TypeError):
        return record, None  # legacy bare-address record
