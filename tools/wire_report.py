"""``make wire``: run a 2-shard replicated kvstore fit and print the
wire-bandwidth books — per-op byte split (header vs payload), codec
wall, RPCs per flush, and the explicitly-labeled projected binary-wire
savings line.

Drives the PR-15 wire observability plane end to end on the CPU
backend: two primary+follower replica groups (followers attached via
live state transfer, sync replication so the ack path is on the books
too), an instrumented ``ShardedTrainer.fit`` through ``dist_async``,
then :func:`mxnet_tpu.observability.wire.format_wire_report`.  Exits
non-zero unless

- the per-op byte books reconcile with the socket-level ground truth
  (``kv_socket_bytes_total``) within 1%, and
- foreground codec seconds reconcile against the attribution ``kv``
  phase (encode/decode happens inside ``att.phase("kv")``),

the same falsifiability contract tier-1 enforces.

Run:  python tools/wire_report.py
"""

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_TPU_METRICS", "1")
os.environ["MXNET_TPU_KV_REPL_SYNC"] = "1"
os.environ.setdefault("MXNET_TPU_PS_SECRET", "wire-report")


def main():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    import mxnet_tpu as mx
    from mxnet_tpu import kvstore_async as ka
    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.observability import wire as owire
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    secret = os.environ["MXNET_TPU_PS_SECRET"]
    servers, addrs = [], []
    for shard in range(2):
        pri = ka.AsyncServer(server_id=shard * 2, secret=secret).start()
        fol = ka.AsyncServer(server_id=shard * 2 + 1,
                             secret=secret).start()
        fol.rejoin(pri.address)
        servers += [pri, fol]
        addrs.append("%s|%s" % (pri.address, fol.address))
    os.environ["MXNET_TPU_ASYNC_PS_ADDRS"] = ",".join(addrs)
    ka.reset_membership()

    B, D = 8, 6
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(net, num_hidden=8, name="fc2"),
        name="softmax")
    kv = mx.kv.create("dist_async")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1,
                                      rescale_grad=1.0 / B, wd=0.0))
    rs = np.random.RandomState(3)
    it = NDArrayIter({"data": rs.randn(32, D).astype(np.float32)},
                     {"softmax_label":
                      rs.randint(0, 8, (32,)).astype(np.float32)},
                     batch_size=B)
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    tr = ShardedTrainer(net, mesh, data_shapes={"data": (B, D)},
                        label_shapes={"softmax_label": (B,)},
                        rescale_grad=1.0 / B)
    tr.fit(it, num_epoch=2, seed=5, log_every=0, kvstore=kv)
    for s in servers:
        s.stop()

    print("Wire-bandwidth books (2-shard replicated fit):")
    print(owire.format_wire_report())
    print()

    failed = False
    ok, wire_b, sock_b = owire.wire_reconciles(tol=0.01)
    if not ok:
        failed = True
        print("FAIL: byte books (%d B) do not reconcile with the "
              "socket truth (%d B) within 1%%" % (wire_b, sock_b))
    else:
        print("byte books reconcile with the socket truth: "
              "%d B vs %d B" % (wire_b, sock_b))
    cok, codec_kv, kv_phase = owire.codec_reconciles()
    if not cok:
        failed = True
        print("FAIL: foreground codec wall (%.4fs) exceeds the "
              "attribution kv phase (%.4fs)" % (codec_kv, kv_phase))
    else:
        print("codec wall reconciles with the attribution kv phase: "
              "%.4fs within %.4fs" % (codec_kv, kv_phase))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
