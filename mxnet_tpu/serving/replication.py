"""Serving replication and brownout: replica groups + a failover router.

One replica is a single point of failure; the serving tier runs a
**replica group** — N schedulers hosting the same models — and a
router that spreads load round-robin and, when a replica dies, retries
its accepted-but-unanswered requests on a peer.  The contract is
brownout, not blackout:

- **Accepted requests are never dropped.**  A request a dead replica
  had admitted fails over to a live peer with ``force=True`` — the
  peer re-admits it past its own overload/drain shedding, because the
  request already cost the caller an accept.
- **New load sheds gracefully.**  With a replica gone the survivors'
  queues fill sooner; the overflow is shed with typed 429/503, every
  shed accounted in ``serving_rejected_total``.

Membership reuses the PR-3 machinery in ``kvstore_async``: the group
publishes ``serving:<group>`` records through ``_membership_publish``
(epoch-monotonic, replica lists merge), a fenced replica's epoch is
left behind so a zombie refuses new work, and liveness is the same
heartbeat idea — every scheduler's dispatch loop beats ``last_beat``,
and :meth:`ReplicaGroup.detect` fences any replica whose beat went
stale.  ``serving_failover_total`` counts fences;
``serving_replica_up{replica}`` tracks liveness for the exposition.

With ``isolated_metrics=True`` each replica gets its own metrics
registry, and :meth:`ReplicaGroup.federation_targets` hands them to
``observability.federation`` under the standard ``{shard, role,
epoch}`` identity — one exposition, per-replica serving rows.

**Elastic scale** (PR-11): the group is no longer frozen at its launch
size.  :meth:`ReplicaGroup.grow` adds replicas live — each new
scheduler is stamped with every registered model (which therefore must
have been registered with a *factory*, not a backend list) and joins
membership at a bumped epoch.  :meth:`ReplicaGroup.shrink` is
drain-before-remove: the victims stop admitting, finish every accepted
request, and only then retire quietly — no failover counted, nothing
dropped.  Both actions pass the ``serving.scale`` chaos site *before*
any membership change, so an injected fault aborts the action with the
group intact.  :meth:`ReplicaGroup.capacity` is what an autoscaler's
``size()`` should report: live replicas only — :meth:`detect` reaps
fenced replicas that never re-registered (slot tombstoned to ``None``,
indices stay stable) so a shrink after a failover never counts zombies
toward capacity.
"""

from __future__ import annotations

import threading
import time

from ..observability.events import emit as _emit_event
from ..observability import metrics as _metrics
from . import admission as _admission
from . import tenancy as _tenancy
from .scheduler import Scheduler

__all__ = ["ReplicaGroup", "ServingRouter"]

_M_FAILOVER = _metrics.counter(
    "serving_failover_total",
    "Replica fences: a dead/stale replica removed from its group",
    ["group"])
_M_UP = _metrics.gauge(
    "serving_replica_up",
    "1 while the serving replica is live, 0 once fenced", ["replica"])


def _group_key(group):
    return "serving:%s" % group


class ReplicaGroup(object):
    """N serving replicas (schedulers) behind one membership record.

    ``isolated_metrics=True`` gives each replica a private
    ``observability.metrics.Registry`` so federation can render them as
    distinct members; the default shares the process-global registry
    (the single-process common case).
    """

    def __init__(self, replicas=2, group="serving",
                 isolated_metrics=False, scheduler_cls=None,
                 tenant_policy=None):
        from .. import kvstore_async as _kv

        self.group = group
        self.epoch = 0
        self._lock = threading.Lock()
        self._fenced = set()
        self._isolated = bool(isolated_metrics)
        # one scheduler class for the whole group: classifier lanes by
        # default, GenerationScheduler for a generation group (its
        # register() accepts the classifier-shaped signature)
        self._scheduler_cls = scheduler_cls or Scheduler
        # ONE tenant policy shared by every replica (grown ones too):
        # a tenant's quota bounds the tenant, not tenant × replicas
        self.tenant_policy = (tenant_policy if tenant_policy is not None
                              else _tenancy.TenantPolicy())
        self._models = {}    # name -> (factory|None, buckets, max_queue)
        self.registries = []
        self.schedulers = []
        for i in range(int(replicas)):
            reg = _metrics.Registry() if isolated_metrics else None
            self.registries.append(reg)
            sched = self._scheduler_cls(
                metrics_registry=reg, name="%s/%d" % (group, i),
                tenant_policy=self.tenant_policy)
            self.schedulers.append(sched)
            _M_UP.labels(sched.name).set(1)
        _kv._membership_publish(
            _group_key(group), self.epoch,
            [s.name for s in self.schedulers],
            primary=self.schedulers[0].name)

    # -- models -------------------------------------------------------

    def register(self, name, backends, buckets=None, max_queue=None,
                 tenant_weights=None):
        """Register ``name`` on every replica.  ``backends`` is either
        a list (one backend per replica — each replica needs its OWN
        Predictor/ExportedModel, executors are not shared) or a
        zero-arg factory called once per replica.  Factories are
        remembered so :meth:`grow` can stamp the model onto replicas
        added later; list registrations pin the group size."""
        factory = backends if callable(backends) else None
        targets = [s for s in self.schedulers if s is not None]
        if callable(backends):
            backends = [backends() for _ in targets]
        if len(backends) != len(targets):
            from ..base import MXNetError

            raise MXNetError(
                "group %r has %d replicas, got %d backends"
                % (self.group, len(targets), len(backends)))
        with self._lock:
            self._models[name] = (factory, buckets, max_queue,
                                  tenant_weights)
        for sched, backend in zip(targets, backends):
            sched.register(name, backend, buckets=buckets,
                           max_queue=max_queue,
                           tenant_weights=tenant_weights)

    def warmup(self, name):
        """Pre-bind every bucket on every live replica."""
        for _, sched in self.live():
            sched.warmup(name)

    # -- membership ---------------------------------------------------

    def live(self):
        """``[(index, scheduler)]`` for replicas not yet fenced."""
        with self._lock:
            fenced = set(self._fenced)
        return [(i, s) for i, s in enumerate(self.schedulers)
                if s is not None and i not in fenced and s.alive]

    def capacity(self):
        """Live replica count — the ``size()`` an autoscaler should
        bound on.  Fenced and reaped zombies never count."""
        return len(self.live())

    def membership(self):
        from .. import kvstore_async as _kv

        return _kv._membership_lookup(_group_key(self.group))

    def kill(self, index):
        """Crash replica ``index`` (chaos drills): queued requests fail
        with ``ReplicaDeadError`` for the router to retry, then the
        group fences it out of membership."""
        if self.schedulers[index] is None:
            return
        self.schedulers[index].kill()
        self.fence(index)

    def fence(self, index):
        """Remove replica ``index`` from the group: bump the membership
        epoch past it (PR-3 monotonic publish — the zombie's old epoch
        can never win again), fail anything it still holds, and account
        the failover.  Idempotent."""
        from .. import kvstore_async as _kv

        with self._lock:
            if index in self._fenced:
                return
            zombie = self.schedulers[index]
            if zombie is None:
                return
            self._fenced.add(index)
            self.epoch += 1
            epoch = self.epoch
            fenced = set(self._fenced)
        zombie.fence(epoch)
        _M_UP.labels(zombie.name).set(0)
        _M_FAILOVER.labels(self.group).inc()
        _emit_event("serving.fence", group=self.group, replica=zombie.name,
                     index=index, epoch=epoch)
        survivors = [s.name for i, s in enumerate(self.schedulers)
                     if s is not None and i not in fenced]
        for i, s in enumerate(self.schedulers):
            if s is not None and i not in fenced:
                s.epoch = epoch
        _kv._membership_publish(
            _group_key(self.group), epoch, survivors or [zombie.name],
            primary=survivors[0] if survivors else zombie.name)

    def detect(self, heartbeat_timeout_s=1.0):
        """Heartbeat sweep: fence every replica whose dispatch loops
        stopped beating, then **reap** fenced replicas that never
        re-registered.  Returns the indices fenced this sweep.

        Only ``last_beat`` of a replica with dispatch lanes counts —
        a freshly grown replica with no model registered yet has no
        loop to beat and must not be fenced for it.

        The reap half fixes the shrink-after-failover hazard: a fenced
        zombie used to sit in ``schedulers`` forever, counting toward
        any ``len()``-based capacity view.  A fenced replica that is
        still dead when a sweep runs (no rejoin re-registered its
        slot) is retired for good — its slot is tombstoned to ``None``
        (indices stay stable for routers), its per-replica registry
        dropped from federation."""
        now = time.monotonic()
        with self._lock:
            fenced = set(self._fenced)
        # NOT live(): a replica that died without being fenced is exactly
        # what this sweep exists to find
        stale = [i for i, s in enumerate(self.schedulers)
                 if s is not None and i not in fenced
                 and (not s.alive
                      or (s._lanes
                          and now - s.last_beat > heartbeat_timeout_s))]
        for i in stale:
            self.fence(i)
        with self._lock:
            for i in self._fenced:
                s = self.schedulers[i]
                if s is not None and not s.alive:
                    self.schedulers[i] = None
                    self.registries[i] = None
        return stale

    # -- elastic scale ------------------------------------------------

    def _advance_epoch(self):
        """Bump the membership epoch and publish the live roster —
        every scale action is epoch-fenced exactly like a failover."""
        from .. import kvstore_async as _kv

        with self._lock:
            self.epoch += 1
            epoch = self.epoch
            fenced = set(self._fenced)
        names = [s.name for i, s in enumerate(self.schedulers)
                 if s is not None and i not in fenced]
        for i, s in enumerate(self.schedulers):
            if s is not None and i not in fenced:
                s.epoch = epoch
        if names:
            _kv._membership_publish(_group_key(self.group), epoch,
                                    names, primary=names[0])
        return epoch

    def grow(self, n=1):
        """Add ``n`` replicas to the group, live.

        Every registered model is stamped onto each newcomer, which
        requires the model to have been registered with a *factory*
        (a backend list can't mint executors for replicas that didn't
        exist yet).  New replicas take fresh indices at the end —
        existing routing is untouched — and the whole grow lands under
        one bumped membership epoch.  Returns ``{"epoch", "added"}``
        (actuator contract: the epoch rides into the autoscaler's
        flight bundle)."""
        from .. import chaos as _chaos
        from ..base import MXNetError

        _chaos.visit("serving.scale", name="grow:%s" % self.group)
        with self._lock:
            models = dict(self._models)
        pinned = sorted(name for name, (fac, _, _, _) in models.items()
                        if fac is None)
        if pinned:
            raise MXNetError(
                "cannot grow group %r: model(s) %s were registered "
                "with a backend list, not a factory — the group size "
                "is pinned" % (self.group, ", ".join(pinned)))
        added = []
        for _ in range(int(n)):
            with self._lock:
                idx = len(self.schedulers)
                reg = _metrics.Registry() if self._isolated else None
                sched = self._scheduler_cls(
                    metrics_registry=reg,
                    name="%s/%d" % (self.group, idx),
                    tenant_policy=self.tenant_policy)
                self.registries.append(reg)
                self.schedulers.append(sched)
            for name, (factory, buckets, max_queue,
                       tenant_weights) in models.items():
                sched.register(name, factory(), buckets=buckets,
                               max_queue=max_queue,
                               tenant_weights=tenant_weights)
            _M_UP.labels(sched.name).set(1)
            added.append(idx)
        epoch = self._advance_epoch()
        _emit_event("serving.resize", group=self.group, action="grow",
                     added=len(added), epoch=epoch)
        return {"epoch": epoch, "added": added}

    def shrink(self, n=1, timeout=10.0):
        """Remove ``n`` replicas, drain-before-remove: the victims
        (highest live indices) stop admitting, finish every accepted
        request (bounded by ``timeout`` seconds each), and only then
        retire — quietly: no ``serving_failover_total`` tick, because
        a voluntary scale-down is not a failover.  Refuses to remove
        the last live replica.  Returns ``{"epoch", "removed"}``."""
        from .. import chaos as _chaos
        from ..base import MXNetError

        _chaos.visit("serving.scale", name="shrink:%s" % self.group)
        n = int(n)
        live = self.live()
        if n >= len(live):
            raise MXNetError(
                "shrink(%d) would empty group %r (%d live replica(s))"
                % (n, self.group, len(live)))
        victims = live[len(live) - n:]
        for _, sched in victims:          # stop admitting everywhere
            sched.drain()                 # first, then wait queues dry
        removed = []
        for idx, sched in victims:
            sched.close(timeout=timeout)  # drains queues, joins loops
            with self._lock:
                self._fenced.add(idx)
            _M_UP.labels(sched.name).set(0)
            removed.append(idx)
        epoch = self._advance_epoch()
        for _, sched in victims:
            # queues are empty, so the fence fails nothing — it only
            # turns the retiree into a refusing zombie at the new epoch
            sched.fence(epoch)
        _emit_event("serving.resize", group=self.group, action="shrink",
                     removed=len(removed), epoch=epoch)
        return {"epoch": epoch, "removed": removed}

    # -- observability ------------------------------------------------

    def federation_targets(self):
        """Per-replica federation targets (``isolated_metrics=True``):
        each replica's registry under ``{shard, role, epoch}``."""
        targets = []
        for i, s in enumerate(self.schedulers):
            if s is None or self.registries[i] is None:
                continue
            targets.append({"shard": i, "role": "serving",
                            "epoch": s.epoch,
                            "registry": self.registries[i]})
        return targets

    def close(self):
        for _, sched in self.live():
            sched.close()


class ServingRouter(object):
    """Round-robin request router with peer failover.

    Sheds (:class:`~.admission.ServerOverloadedError` /
    :class:`~.admission.ServerDrainingError`) try the next replica and
    only surface when every replica shed.  A replica that dies holding
    an accepted request is fenced and the request re-admitted on a peer
    with ``force=True`` — the brownout guarantee."""

    def __init__(self, group):
        self._group = group
        self._rr = 0
        self._lock = threading.Lock()

    def _rotation(self):
        live = self._group.live()
        if not live:
            return []
        with self._lock:
            start = self._rr
            self._rr += 1
        return live[start % len(live):] + live[:start % len(live)]

    @staticmethod
    def _remaining_ms(req):
        """Carry the original absolute deadline onto the retry."""
        if req.deadline is None:
            return 0  # deadline_from_ms(0) -> no deadline
        return max((req.deadline - time.monotonic()) * 1e3, 0.001)

    def request(self, model, inputs, deadline_ms=None, timeout=30.0,
                tenant=None):
        shed = None
        for index, sched in self._rotation():
            try:
                req = sched.submit(model, inputs, deadline_ms=deadline_ms,
                                   tenant=tenant)
            except _admission.ReplicaDeadError:
                self._group.fence(index)
                continue
            except (_admission.ServerOverloadedError,
                    _admission.ServerDrainingError) as exc:
                shed = exc
                continue
            try:
                return req.result(timeout=timeout)
            except _admission.ReplicaDeadError:
                # accepted but unanswered: fence the replica, finish
                # the request on a peer — never drop accepted work
                self._group.fence(index)
                return self._retry_on_peer(model, req, timeout)
        if shed is not None:
            raise shed
        raise _admission.ReplicaDeadError(
            "group %r has no live serving replica" % self._group.group)

    def _retry_on_peer(self, model, req, timeout):
        for index, sched in self._group.live():
            try:
                peer = sched.submit(model, req.inputs,
                                    deadline_ms=self._remaining_ms(req),
                                    force=True, tenant=req.tenant)
                return peer.result(timeout=timeout)
            except _admission.ReplicaDeadError:
                self._group.fence(index)
        raise _admission.ReplicaDeadError(
            "request to %r accepted by a dead replica and no peer is "
            "left in group %r" % (model, self._group.group))
