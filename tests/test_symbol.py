"""Symbol tests (parity model: reference ``tests/python/unittest/test_symbol.py``
+ ``test_infer_shape.py``)."""

import numpy as np

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_symbol_compose():
    net = _mlp()
    args = net.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
                    "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]


def test_symbol_infer_shape():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 100))
    assert dict(zip(net.list_arguments(), arg_shapes))["fc1_weight"] == (128, 100)
    assert out_shapes == [(32, 10)]
    assert aux_shapes == []


def test_symbol_infer_shape_conv():
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16, pad=(1, 1),
                              name="conv")
    bn = mx.sym.BatchNorm(conv, name="bn")
    pool = mx.sym.Pooling(bn, kernel=(2, 2), stride=(2, 2), pool_type="max")
    arg_shapes, out_shapes, aux_shapes = pool.infer_shape(data=(2, 3, 8, 8))
    d = dict(zip(pool.list_arguments(), arg_shapes))
    assert d["conv_weight"] == (16, 3, 3, 3)
    assert d["conv_bias"] == (16,)
    assert d["bn_gamma"] == (16,)
    assert out_shapes == [(2, 16, 4, 4)]
    assert aux_shapes == [(16,), (16,)]


def test_symbol_internals():
    net = _mlp()
    internals = net.get_internals()
    assert "fc1_output" in internals.list_outputs()
    fc1 = internals["fc1_output"]
    assert fc1.list_arguments() == ["data", "fc1_weight", "fc1_bias"]


def test_symbol_grouping():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = a + b
    g = mx.sym.Group([c, a * b])
    assert len(g.list_outputs()) == 2


def test_symbol_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    net2 = mx.sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    # shape inference still works after round trip
    _, out_shapes, _ = net2.infer_shape(data=(4, 50))
    assert out_shapes == [(4, 10)]


def test_symbol_attr():
    with mx.AttrScope(ctx_group="dev1"):
        a = mx.sym.Variable("a")
    assert a.attr("ctx_group") == "dev1"
    data = mx.sym.Variable("data", lr_mult=2.0)
    assert data.attr("__lr_mult__") == "2.0"


def test_symbol_arithmetic_eval():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = 2.0 * a + b ** 2 - 1.0
    ex = c.bind(mx.cpu(), {"a": mx.nd.array([1.0, 2.0]), "b": mx.nd.array([3.0, 4.0])})
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, [2 + 9 - 1, 4 + 16 - 1], rtol=1e-6)


def test_symbol_save_load(tmp_path):
    net = _mlp()
    fname = str(tmp_path / "sym.json")
    net.save(fname)
    net2 = mx.sym.load(fname)
    assert net2.list_arguments() == net.list_arguments()


def test_load_json_legacy_variants():
    # reference-era JSON quirks: per-node "param" (not "attrs"), 3-element
    # input entries [id, idx, version], versioned heads
    # (legacy_json_util.cc back-compat tier)
    import json

    legacy = {
        "nodes": [
            {"op": "null", "name": "data", "inputs": [], "param": {}},
            {"op": "null", "name": "fc_weight", "inputs": [], "param": {}},
            {"op": "null", "name": "fc_bias", "inputs": [], "param": {}},
            {"op": "FullyConnected", "name": "fc",
             "param": {"num_hidden": "4", "no_bias": "False"},
             "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
            {"op": "null", "name": "softmax_label", "inputs": [],
             "param": {}},
            {"op": "SoftmaxOutput", "name": "softmax", "param": {},
             "inputs": [[3, 0, 0], [4, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 2, 4],
        "heads": [[5, 0, 0]],
        "attrs": {"mxnet_version": ["int", 905]},
    }
    sym = mx.sym.load_json(json.dumps(legacy))
    assert sym.list_arguments() == ["data", "fc_weight", "fc_bias",
                                    "softmax_label"]
    ex = sym.bind(mx.cpu(), {
        "data": mx.nd.array(np.ones((2, 3), np.float32)),
        "fc_weight": mx.nd.array(np.ones((4, 3), np.float32)),
        "fc_bias": mx.nd.zeros((4,)),
        "softmax_label": mx.nd.zeros((2,)),
    })
    out = ex.forward()[0].asnumpy()
    assert out.shape == (2, 4)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)


def test_s2d_resnet_json_roundtrip():
    """The s2d stem graph (Pad + 0-code reshapes) survives JSON
    serialization and produces identical outputs after reload."""
    import numpy as np

    from mxnet_tpu.models import resnet

    sym = resnet.get_symbol(num_classes=3, num_layers=18,
                            image_shape=(3, 64, 64), layout="NHWC",
                            stem="s2d")
    sym2 = mx.sym.load_json(sym.tojson())
    assert sym2.list_arguments() == sym.list_arguments()
    ex1 = sym.simple_bind(mx.cpu(), data=(1, 3, 64, 64), grad_req="null")
    ex2 = sym2.simple_bind(mx.cpu(), data=(1, 3, 64, 64), grad_req="null")
    np.random.seed(5)
    for k, v1 in ex1.arg_dict.items():
        val = np.random.randn(*v1.shape).astype(np.float32) * 0.1
        v1[:] = val
        ex2.arg_dict[k][:] = val
    o1 = ex1.forward(is_train=False)[0].asnumpy()
    o2 = ex2.forward(is_train=False)[0].asnumpy()
    np.testing.assert_array_equal(o1, o2)


def test_keyword_inputs_slot_aligned():
    """Keyword tensor inputs bind by NAME with slot alignment: an omitted
    middle input gets an auto-created Variable in ITS slot — a later
    keyword Symbol never shifts into the wrong position.  Covers both
    static arg_names ops and attr-dependent input_names_fn ops (the
    C-ABI compose path sends all inputs as keywords)."""
    fd = mx.sym.Variable("fd")
    fb = mx.sym.Variable("fb")
    f = mx.sym.FullyConnected(bias=fb, data=fd, num_hidden=8, name="fc")
    assert f.list_arguments() == ["fd", "fc_weight", "fb"]
    # no_bias trims the dynamic name list
    g = mx.sym.FullyConnected(data=fd, num_hidden=8, no_bias=True, name="g")
    assert g.list_arguments() == ["fd", "g_weight"]


def test_torch_module_keyword_compose():
    """TorchModule's torch-param input slots are named after the module's
    parameters (dynamic input_names_fn); keyword-only compose — the C-ABI
    path — must wire them correctly, diagnose num_params mismatches with
    the registry's own error, and slot-align omitted params."""
    import pytest

    pytest.importorskip("torch")
    from mxnet_tpu.base import MXNetError

    d = mx.sym.Variable("d")
    w = mx.sym.Variable("w")
    b = mx.sym.Variable("b")
    s = mx.sym.TorchModule(data_0=d, weight=w, bias=b,
                           module="nn.Linear(4,3)", num_data=1,
                           num_params=2, name="tm")
    assert s.list_arguments() == ["d", "w", "b"]
    # omitted middle name: bias stays in the bias slot
    s2 = mx.sym.TorchModule(data_0=d, bias=b, module="nn.Linear(4,3)",
                            num_data=1, num_params=2, name="tm2")
    assert s2.list_arguments() == ["d", "tm2_weight", "b"]
    # registry validation propagates (not masked as unknown-attribute)
    with pytest.raises(MXNetError, match="num_params=3"):
        mx.sym.TorchModule(data_0=d, weight=w, bias=b,
                           module="nn.Linear(4,3)", num_params=3)
    # numerics through the keyword-composed graph
    rng = np.random.RandomState(0)
    dv = rng.randn(2, 4).astype(np.float32)
    wv = rng.randn(3, 4).astype(np.float32)
    ex = s.bind(mx.cpu(), {"d": mx.nd.array(dv), "w": mx.nd.array(wv),
                           "b": mx.nd.zeros((3,))})
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, dv @ wv.T, rtol=1e-5, atol=1e-5)
