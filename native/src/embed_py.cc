/*! Definitions for the shared embedded-CPython plumbing (see embed_py.h). */
#include "embed_py.h"

#include <dlfcn.h>

#include <mutex>

namespace mxtpu_capi {

namespace {
thread_local std::string g_err;
std::once_flag g_py_once;

/* When the HOST process dlopens us RTLD_LOCAL (perl's DynaLoader, ruby,
 * node-ffi, ...), libpython is pulled in as a private dependency and its
 * symbols are invisible to the extension modules (math, numpy, ...) the
 * embedded interpreter later dlopens — imports die with
 * "undefined symbol: PyFloat_Type".  Re-opening libpython RTLD_GLOBAL
 * promotes its symbols to the global scope before Py_Initialize.  A
 * no-op when the embedding binary linked us normally (the C clients). */
void promote_libpython() {
  Dl_info info;
  if (dladdr(reinterpret_cast<void *>(&Py_IsInitialized), &info) &&
      info.dli_fname) {
    dlopen(info.dli_fname, RTLD_NOW | RTLD_GLOBAL | RTLD_NOLOAD);
  }
}
}  // namespace

void ensure_python() {
  std::call_once(g_py_once, [] {
    if (!Py_IsInitialized()) {
      promote_libpython();
      Py_InitializeEx(0);
      /* Release the GIL acquired by initialization so PyGILState_Ensure
       * works uniformly afterwards. */
      PyEval_SaveThread();
    }
  });
}

std::string py_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *u = PyUnicode_AsUTF8(s);
      if (u) msg = u; /* NULL on encode failure: keep default */
      else PyErr_Clear();
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return msg;
}

void set_err(const std::string &m) { g_err = m; }

const char *last_err() { return g_err.c_str(); }

}  // namespace mxtpu_capi
