"""chaos-site: every site string handed to the chaos plant/fire APIs
exists in ``chaos.SITES`` — including sites spelled inside
``MXNET_TPU_CHAOS`` spec strings and in docs code blocks.

The chaos registry already rejects unknown sites at runtime
(``_Rule.__init__``), but only when that code path *runs*: a typo'd site
in a rarely-exercised test, a doc example, or an env-spec string fails
silently (the rule simply never fires) — the worst failure mode for
fault-injection coverage.  This rule closes that statically.

Checked call forms: ``chaos.visit("<site>", ...)``,
``chaos.inject("<site>", ...)``, ``chaos.corrupt_file("<site>", ...)``
(any module alias whose last segment is ``chaos``/``_chaos``).  Checked
string forms: any literal shaped like an ``MXNET_TPU_CHAOS`` spec —
comma-separated ``site:mode[:...]`` entries whose mode is one of
``drop|delay|raise|corrupt``.
"""

from __future__ import annotations

import ast
import os
import re

from ..core import Finding, dotted_name, iter_code_blocks

RULE = "chaos-site"

_CHAOS_FUNCS = {"visit", "inject", "corrupt_file"}
_SPEC_ENTRY_RE = re.compile(
    r"^([A-Za-z_][\w.]*):(drop|delay|raise|corrupt)([:@]|$)")
_MD_CALL_RE = re.compile(
    r"\bchaos\.(?:visit|inject|corrupt_file)\(\s*[\"']([^\"']+)[\"']")


def _spec_sites(value):
    """Site names from an ``MXNET_TPU_CHAOS``-shaped spec string; empty
    when the string is not spec-shaped (every entry must match)."""
    entries = [e.strip() for e in value.split(",") if e.strip()]
    if not entries:
        return []
    sites = []
    for e in entries:
        m = _SPEC_ENTRY_RE.match(e)
        if not m:
            return []
        sites.append(m.group(1))
    return sites


def check_chaos_sites(project):
    sites = project.chaos_sites()
    if sites is None:
        return   # no chaos module in this tree — nothing to check

    chaos_rel = os.path.join("mxnet_tpu", "chaos.py")
    for sf in project.py_files:
        if sf.tree is None or sf.path.startswith(
                os.path.join("tools", "graftcheck")):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                dn = dotted_name(node.func)
                if dn and dn.rsplit(".", 1)[-1] in _CHAOS_FUNCS \
                        and dn.split(".")[-2:-1] in (["chaos"],
                                                     ["_chaos"]) \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    site = node.args[0].value
                    if site not in sites:
                        yield Finding(
                            sf.path, node.lineno, RULE,
                            "unknown chaos site %r (not in chaos.SITES)"
                            % site)
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and sf.path != chaos_rel:
                for site in _spec_sites(node.value):
                    if site not in sites:
                        yield Finding(
                            sf.path, node.lineno, RULE,
                            "MXNET_TPU_CHAOS spec names unknown chaos "
                            "site %r (not in chaos.SITES)" % site)

    # docs code blocks (and the chaos module's own docstring example is
    # covered above via the literal scan)
    for sf in project.md_files:
        for start, block in iter_code_blocks(sf.text):
            for off, line in enumerate(block.splitlines()):
                for m in _MD_CALL_RE.finditer(line):
                    if m.group(1) not in sites:
                        yield Finding(
                            sf.path, start + off, RULE,
                            "docs code block uses unknown chaos site %r "
                            "(not in chaos.SITES)" % m.group(1))
                for part in re.findall(
                        r"MXNET_TPU_CHAOS=[\"']?([^\"'\s]+)", line):
                    for site in _spec_sites(part):
                        if site not in sites:
                            yield Finding(
                                sf.path, start + off, RULE,
                                "docs code block MXNET_TPU_CHAOS spec "
                                "names unknown chaos site %r" % site)
