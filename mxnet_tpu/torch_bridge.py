"""Torch interop (parity: reference ``python/mxnet/torch.py`` +
``plugin/torch`` — calling Torch tensor functions and nn modules on MXNet
NDArrays).

The reference binds LuaTorch through a C plugin; here the baked-in PyTorch
(CPU) interops zero-ceremony via numpy: ``mx.th.call`` applies any
``torch.*`` function to NDArrays; ``TorchModule`` wraps a ``torch.nn``
module for inference inside the imperative flow.  Device arrays round-trip
through host — torch has no TPU backend, so this is a host-side escape
hatch exactly like the reference's CPU Torch path.
"""

from __future__ import annotations

from .base import MXNetError
from .ndarray import NDArray, array

__all__ = ["call", "TorchModule", "available"]


def _torch():
    try:
        import torch

        return torch
    except ImportError:
        raise MXNetError("torch is not installed")


def available():
    try:
        import torch  # noqa: F401

        return True
    except ImportError:
        return False


def call(fname, *args, **kwargs):
    """Apply ``torch.<fname>`` to the given arrays (parity: the generated
    ``mxnet.th.*`` wrappers).  NDArray args convert to torch tensors; NDArray
    results convert back."""
    torch = _torch()
    fn = torch
    for part in fname.split("."):
        fn = getattr(fn, part, None)
        if fn is None:
            raise MXNetError("no torch function %r" % fname)

    def to_t(a):
        # copy: jax owns the source buffer; in-place torch ops (abs_, add_)
        # must never write through into XLA memory
        return (torch.from_numpy(a.asnumpy().copy())
                if isinstance(a, NDArray) else a)

    out = fn(*[to_t(a) for a in args],
             **{k: to_t(v) for k, v in kwargs.items()})
    if isinstance(out, (list, tuple)):
        return type(out)(array(o.numpy()) if hasattr(o, "numpy") else o
                         for o in out)
    return array(out.numpy()) if hasattr(out, "numpy") else out


class TorchModule(object):
    """Wrap a ``torch.nn.Module`` for forward inference on NDArrays
    (parity: ``plugin/torch`` TorchModuleOp)."""

    def __init__(self, module):
        import copy

        torch = _torch()
        if not isinstance(module, torch.nn.Module):
            raise MXNetError("expected a torch.nn.Module")
        # deep copy so eval() (and inference use) never mutates the caller's
        # module mid-training
        self.module = copy.deepcopy(module).eval()

    def __call__(self, *inputs):
        torch = _torch()
        tins = [torch.from_numpy(i.asnumpy().copy()) if isinstance(i, NDArray)
                else i for i in inputs]
        with torch.no_grad():
            out = self.module(*tins)
        if isinstance(out, (list, tuple)):
            return [array(o.numpy()) for o in out]
        return array(out.numpy())
