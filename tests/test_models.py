"""Model zoo smoke tests: shape inference + a forward pass on small inputs
(the reference exercises its symbols via tests/python/train and
benchmark_score.py; here shape-level checks keep CI fast)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models


@pytest.mark.parametrize("net,shape", [
    ("mlp", (2, 1, 28, 28)),
    ("lenet", (2, 1, 28, 28)),
])
def test_small_models_forward(net, shape):
    sym = models.get_symbol(net, num_classes=10)
    exe = sym.simple_bind(ctx=mx.cpu(), data=shape, softmax_label=(shape[0],))
    exe.arg_dict["data"][:] = np.random.uniform(size=shape).astype(np.float32)
    out = exe.forward(is_train=False)[0]
    assert out.shape == (shape[0], 10)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1), 1.0, rtol=1e-4)


@pytest.mark.parametrize("net", ["resnet-18", "resnet-50", "resnext"])
def test_resnet_shapes(net):
    sym = models.get_symbol(net, num_classes=1000)
    arg_shapes, out_shapes, aux_shapes = sym.infer_shape(
        data=(2, 3, 224, 224), softmax_label=(2,))
    assert out_shapes[0] == (2, 1000)


@pytest.mark.parametrize("net", ["alexnet", "vgg", "googlenet",
                                 "inception-bn", "inception-v3",
                                 "inception-resnet-v2"])
def test_big_convnets_infer(net):
    shape = ((2, 3, 299, 299) if net in ("inception-v3",
                                         "inception-resnet-v2")
             else (2, 3, 224, 224))
    sym = models.get_symbol(net, num_classes=1000)
    arg_shapes, out_shapes, aux_shapes = sym.infer_shape(
        data=shape, softmax_label=(2,))
    assert out_shapes[0] == (2, 1000)


def test_resnet_cifar_forward():
    sym = models.get_symbol("resnet", num_classes=10, num_layers=8,
                            image_shape=(3, 28, 28))
    exe = sym.simple_bind(ctx=mx.cpu(), data=(2, 3, 28, 28),
                          softmax_label=(2,))
    exe.arg_dict["data"][:] = np.random.uniform(size=(2, 3, 28, 28)).astype(np.float32)
    # init BN gammas to 1 so the forward is non-degenerate
    for k, v in exe.arg_dict.items():
        if k.endswith("_gamma"):
            v[:] = 1.0
    out = exe.forward(is_train=False)[0]
    assert out.shape == (2, 10)


def test_resnet_bf16():
    sym = models.get_symbol("resnet", num_classes=10, num_layers=8,
                            image_shape=(3, 28, 28), dtype="bfloat16")
    exe = sym.simple_bind(ctx=mx.cpu(), data=(2, 3, 28, 28),
                          softmax_label=(2,))
    out = exe.forward(is_train=False)[0]
    assert out.shape == (2, 10)
    assert str(out.dtype) == "float32"  # loss head cast back


def test_lstm_lm_forward():
    from mxnet_tpu.models import lstm
    s = lstm.get_symbol(num_classes=50, seq_len=7, num_embed=16,
                        num_hidden=16, num_layers=2)
    exe = s.simple_bind(ctx=mx.cpu(), data=(4, 7), softmax_label=(4, 7),
                        type_dict={"data": "int32"})
    exe.arg_dict["data"][:] = np.random.randint(0, 50, size=(4, 7))
    out = exe.forward(is_train=False)[0]
    assert out.shape == (4 * 7, 50)


def test_resnet_s2d_stem_exact_equivalence():
    """stem='s2d' (space-to-depth conv0) is numerically EXACT vs the
    standard 7x7/s2 stem once conv0_weight is mapped with
    convert_stem_to_s2d — whole-network forward parity."""
    import numpy as np

    from mxnet_tpu.models import resnet

    shape = (2, 3, 64, 64)
    std = resnet.get_symbol(num_classes=5, num_layers=18,
                            image_shape=(3, 64, 64), layout="NHWC")
    s2d = resnet.get_symbol(num_classes=5, num_layers=18,
                            image_shape=(3, 64, 64), layout="NHWC",
                            stem="s2d")
    ex1 = std.simple_bind(mx.cpu(), data=shape, grad_req="null")
    np.random.seed(0)
    for name, arr in ex1.arg_dict.items():
        if name != "data":
            arr[:] = np.random.randn(*arr.shape).astype(np.float32) * 0.1
    args2 = resnet.convert_stem_to_s2d(
        {k: v for k, v in ex1.arg_dict.items() if k != "data"})
    ex2 = s2d.simple_bind(mx.cpu(), data=shape, grad_req="null")
    for name, arr in ex2.arg_dict.items():
        if name != "data":
            arr[:] = args2[name].asnumpy()
    x = np.random.randn(*shape).astype(np.float32)
    ex1.arg_dict["data"][:] = x
    ex2.arg_dict["data"][:] = x
    o1 = ex1.forward(is_train=False)[0].asnumpy()
    o2 = ex2.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(o2, o1, rtol=1e-4, atol=1e-5)


def test_resnet_s2d_stem_backward_parity():
    """Gradients w.r.t. the input match between stems (the transform is a
    linear reparameterization of conv0, so d/d(data) is identical)."""
    import numpy as np

    from mxnet_tpu.models import resnet

    shape = (2, 3, 64, 64)
    kw = dict(num_classes=3, num_layers=18, image_shape=(3, 64, 64),
              layout="NHWC")
    std = resnet.get_symbol(**kw)
    s2d = resnet.get_symbol(stem="s2d", **kw)
    ex1 = std.simple_bind(mx.cpu(), data=shape,
                          softmax_label=(2,), grad_req="write")
    np.random.seed(3)
    for name, arr in ex1.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = np.random.randn(*arr.shape).astype(np.float32) * 0.1
    args2 = resnet.convert_stem_to_s2d(
        {k: v for k, v in ex1.arg_dict.items()
         if k not in ("data", "softmax_label")})
    ex2 = s2d.simple_bind(mx.cpu(), data=shape,
                          softmax_label=(2,), grad_req="write")
    for name, arr in ex2.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = args2[name].asnumpy()
    x = np.random.randn(*shape).astype(np.float32)
    y = np.array([0.0, 2.0], np.float32)
    for ex in (ex1, ex2):
        ex.arg_dict["data"][:] = x
        ex.arg_dict["softmax_label"][:] = y
        ex.forward(is_train=True)
        ex.backward()
    # deeper-layer weight grads are stem-independent
    for k in ("fc1_weight", "stage1_unit1_conv1_weight"):
        np.testing.assert_allclose(ex2.grad_dict[k].asnumpy(),
                                   ex1.grad_dict[k].asnumpy(),
                                   rtol=1e-3, atol=1e-5)
    # conv0 grads agree on the embedded 7x7 support; the zero-padded
    # kernel slots are EXTRA trainable parameters in the s2d layout (they
    # legitimately receive their own gradients)
    g1 = {"conv0_weight": mx.nd.array(ex1.grad_dict["conv0_weight"].asnumpy())}
    g1m = resnet.convert_stem_to_s2d(g1)["conv0_weight"].asnumpy()
    ones = {"conv0_weight": mx.nd.array(
        np.ones_like(ex1.grad_dict["conv0_weight"].asnumpy()))}
    support = resnet.convert_stem_to_s2d(ones)["conv0_weight"] \
        .asnumpy().astype(bool)
    g2 = ex2.grad_dict["conv0_weight"].asnumpy()
    np.testing.assert_allclose(g2[support], g1m[support], rtol=1e-3,
                               atol=1e-5)


def test_benchmark_score_device_loop_smoke():
    """--device-loop scoring (all batches in one jitted fori_loop; the
    dispatch-free methodology of docs/PERF.md) runs end to end and
    produces a positive throughput on a tiny net."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable,
         os.path.join(repo, "examples", "image_classification",
                      "benchmark_score.py"),
         "--network", "alexnet", "--batch-size", "2", "--num-batches", "3",
         "--device-loop"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu", MXNET_TPU_PLATFORM="cpu"))
    assert r.returncode == 0, r.stdout + r.stderr
    line = [l for l in r.stderr.splitlines() + r.stdout.splitlines()
            if "images/sec" in l]
    assert line, (r.stdout, r.stderr)
    assert float(line[0].rsplit(" ", 1)[1]) > 0


def test_transformer_fused_ce_head_matches_softmax_grads():
    """transformer.get_symbol(head='fused_ce') trains through
    ShardedTrainer with IDENTICAL parameter updates to the softmax head
    (same math, chunked; the softmax head's unused pred_bias aside) —
    the long-context configuration that never materializes [T, vocab]
    logits."""
    import jax
    from jax.sharding import Mesh

    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    batch, seq, vocab = 2, 32, 29
    rng = np.random.RandomState(0)
    data = rng.randint(0, vocab, (batch, seq)).astype(np.int32)
    label = rng.randint(0, vocab, (batch, seq)).astype(np.float32)
    results = {}
    for head in ("softmax", "fused_ce"):
        sym = transformer.get_symbol(
            num_classes=vocab, seq_len=seq, num_embed=16, num_heads=2,
            num_layers=2, head=head, ce_chunk=16)
        mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1),
                    ("data", "seq"))
        tr = ShardedTrainer(
            sym, mesh, data_shapes={"data": (batch, seq)},
            label_shapes={"softmax_label": (batch, seq)},
            type_dict={"data": "int32"}, learning_rate=0.2, momentum=0.9,
            rescale_grad=1.0 / (batch * seq))
        params, moms, aux = tr.init(seed=0)
        if head == "softmax":
            # zero the bias the fused head lacks so updates can align
            params["pred_bias"] = params["pred_bias"] * 0.0
        arrays = tr.place_batch({"data": data, "softmax_label": label})
        step = tr.step_fn()
        # ONE step: after it the softmax head's pred_bias becomes nonzero
        # and the heads legitimately diverge from step 2 on
        outs, params, moms, aux = step(params, moms, aux, arrays,
                                       jax.random.PRNGKey(0))
        results[head] = {k: np.asarray(jax.device_get(v))
                         for k, v in params.items() if k != "pred_bias"}
    for k in results["fused_ce"]:
        np.testing.assert_allclose(
            results["softmax"][k], results["fused_ce"][k],
            rtol=1e-3, atol=1e-4,
            err_msg="param %r diverges between heads" % k)


def test_transformer_moe_ffn_trains():
    """ffn='moe': MoELayer FFNs + grouped aux load-balancing loss.  One
    ShardedTrainer step must run, move the expert weights, and emit a
    finite aux loss; on an expert-axis-less mesh the indexed dispatch
    path executes (the single-chip MoE bench configuration)."""
    import jax
    from jax.sharding import Mesh
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    B, S, d = 2, 16, 32
    sym = transformer.get_symbol(num_classes=50, seq_len=S, num_embed=d,
                                 num_heads=2, num_layers=2, ffn="moe",
                                 num_experts=4, moe_top_k=2)
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    tr = ShardedTrainer(sym, mesh, data_shapes={"data": (B, S)},
                        label_shapes={"softmax_label": (B, S)},
                        type_dict={"data": "int32"}, learning_rate=0.1,
                        rescale_grad=1.0 / (B * S))
    params, moms, aux = tr.init(seed=0)
    rs = np.random.RandomState(0)
    batch = tr.place_batch({
        "data": rs.randint(0, 50, (B, S)).astype(np.int32),
        "softmax_label": rs.randint(0, 50, (B, S)).astype(np.float32)})
    w1_before = np.asarray(params["l0_moe_w1_weight"]).copy()
    step = tr.step_fn()
    outs, params, moms, aux = step(params, moms, aux, batch,
                                   jax.random.PRNGKey(0))
    # outputs: softmax probs + the MakeLoss'd aux loss (finite scalar-ish)
    assert np.all(np.isfinite(np.asarray(outs[-1])))
    assert not np.allclose(np.asarray(params["l0_moe_w1_weight"]),
                           w1_before)
