/*!
 * Engine stress test — the reference's C++ unit tier
 * (tests/cpp/threaded_engine_test.cc pushes thousands of random-dependency
 * ops, WaitForAll, then checks invariants).  Here: random ops over a set of
 * vars, each op atomically bumps counters for its write vars and snapshots
 * its read vars; afterwards we assert (a) all ops ran, (b) per-var write
 * serialization held (no torn read-modify-write).
 */
#include <algorithm>
#include <atomic>
#include <cassert>
#include <cstdio>
#include <cstdlib>
#include <chrono>
#include <random>
#include <thread>
#include <vector>

#include "mxtpu/c_api.h"

namespace {

constexpr int kVars = 16;
constexpr int kOps = 4000;

// Per-var plain (unsynchronized) counter: safe iff the engine serializes
// writers per var.
long g_counter[kVars];
std::atomic<long> g_ops_run{0};

struct OpParam {
  std::vector<int> writes;
};

void OpFn(void *p) {
  auto *param = static_cast<OpParam *>(p);
  for (int v : param->writes) {
    long cur = g_counter[v];
    // widen the race window: if two writers on the same var overlap, the
    // final count comes up short
    for (volatile int i = 0; i < 50; ++i) {
    }
    g_counter[v] = cur + 1;
  }
  g_ops_run.fetch_add(1);
}

void OpDel(void *p) { delete static_cast<OpParam *>(p); }

}  // namespace

int main() {
  std::mt19937 rng(42);
  std::vector<MXTPUVarHandle> vars;
  for (int i = 0; i < kVars; ++i) vars.push_back(mxtpu_var_new());

  std::vector<long> expected(kVars, 0);
  for (int i = 0; i < kOps; ++i) {
    int nr = (int)(rng() % 3);
    int nw = 1 + (int)(rng() % 2);
    std::vector<MXTPUVarHandle> creads, cwrites;
    std::vector<int> widx;
    // pick distinct vars for this op
    std::vector<int> perm(kVars);
    for (int j = 0; j < kVars; ++j) perm[j] = j;
    std::shuffle(perm.begin(), perm.end(), rng);
    for (int j = 0; j < nr; ++j) creads.push_back(vars[perm[j]]);
    for (int j = nr; j < nr + nw; ++j) {
      cwrites.push_back(vars[perm[j]]);
      widx.push_back(perm[j]);
      expected[perm[j]]++;
    }
    auto *param = new OpParam{widx};
    mxtpu_push(OpFn, param, OpDel, creads.data(), (int)creads.size(),
               cwrites.data(), (int)cwrites.size(), (int)(rng() % 5), 0,
               "stress_op");
  }
  mxtpu_wait_all();

  assert(g_ops_run.load() == kOps);
  for (int i = 0; i < kVars; ++i) {
    if (g_counter[i] != expected[i]) {
      std::fprintf(stderr, "var %d: got %ld want %ld — write race!\n", i,
                   g_counter[i], expected[i]);
      return 1;
    }
  }
  // WaitForVar + var deletion paths
  for (auto v : vars) mxtpu_wait_for_var(v);
  for (auto v : vars) mxtpu_var_delete(v);
  mxtpu_wait_all();

  // concurrent WaitForVar + delete-while-pending — the reference's
  // trickiest path (threaded_engine.cc CompleteWriteDependency: the var
  // must stay alive until every queued request, including waiters pushed
  // before the delete, has drained; only then may it free).
  // NaiveEngine runs ops inline on the pushing thread: the spinning gate
  // op would deadlock, and the concurrency being tested doesn't exist.
  for (int round = 0; mxtpu_engine_type() == 0 && round < 25; ++round) {
    MXTPUVarHandle v = mxtpu_var_new();
    std::atomic<int> gate{0}, chain_run{0};
    struct GateParam {
      std::atomic<int> *gate, *run;
    };
    auto gate_fn = +[](void *p) {
      auto *gp = static_cast<GateParam *>(p);
      while (gp->gate->load() == 0) {
      }  // hold the queue open until the main thread releases
      gp->run->fetch_add(1);
    };
    auto bump_fn = +[](void *p) {
      static_cast<GateParam *>(p)->run->fetch_add(1);
    };
    auto del_fn = +[](void *p) { delete static_cast<GateParam *>(p); };
    mxtpu_push(gate_fn, new GateParam{&gate, &chain_run}, del_fn, nullptr,
               0, &v, 1, 0, 0, "gate");
    for (int i = 0; i < 7; ++i)
      mxtpu_push(bump_fn, new GateParam{&gate, &chain_run}, del_fn, nullptr,
                 0, &v, 1, 0, 0, "chain");
    // waiters enqueue read requests behind the (blocked) writer chain
    std::vector<std::thread> waiters;
    for (int i = 0; i < 4; ++i)
      waiters.emplace_back([v] { mxtpu_wait_for_var(v); });
    // delete lands while 8 writers + 4 waiters are pending
    mxtpu_var_delete(v);
    // deterministic, not sleep-based: while the gate op spins NOTHING can
    // drain, so pending == 8 chain + 1 delete + 4 waiter ops exactly when
    // every waiter's request is queued — only then release the gate (a
    // straggler pushing after the drain would touch a freed var)
    while (mxtpu_engine_pending() < 13) {
      std::this_thread::yield();
    }
    gate.store(1);
    for (auto &t : waiters) t.join();
    mxtpu_wait_all();
    if (chain_run.load() != 8) {
      std::fprintf(stderr,
                   "round %d: chain ran %d/8 ops after delete-while-"
                   "pending\n", round, chain_run.load());
      return 1;
    }
  }

  // storage pool reuse (reference tests/cpp/storage_test.cc tier)
  void *p1 = mxtpu_storage_alloc(1 << 16);
  mxtpu_storage_free(p1, 1 << 16);
  void *p2 = mxtpu_storage_alloc(1 << 16);
  assert(p1 == p2 && "pool should recycle the freed block");
  mxtpu_storage_direct_free(p2, 1 << 16);

  std::printf("engine_test: %ld ops, %d workers, engine_type=%d — OK\n",
              g_ops_run.load(), mxtpu_engine_num_workers(),
              mxtpu_engine_type());
  return 0;
}
