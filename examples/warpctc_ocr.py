"""CTC sequence training (parity: reference ``example/warpctc/`` —
LSTM + warp-CTC OCR on generated digit images; the loss here is the
built-in ``ctc_loss`` op, log-space scan replacing the vendored
warp-ctc kernels).

Task: images of LEN digits rendered as column-bar glyphs (each digit d
lights rows proportional to d in a noisy 12-row strip); the unsegmented
image scans left-to-right through an LSTM and CTC aligns the per-column
class posteriors with the digit sequence.  Greedy-decoded sequence
accuracy is the gate.

    python examples/warpctc_ocr.py [--num-epochs 12]
"""

import argparse
import logging
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

def _want_tpu(argv):
    for i, a in enumerate(argv):
        if a == "--tpus" and i + 1 < len(argv):
            return argv[i + 1] != "0"
        if a.startswith("--tpus="):
            return a.split("=", 1)[1] != "0"
    return False


if __name__ == "__main__" and not _want_tpu(sys.argv[1:]):
    # default to the CPU platform before first backend touch: the LSTM
    # unroll compiles slowly through tunneled dev backends (pass --tpus 1
    # on a real TPU runtime)
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

import mxnet_tpu as mx

ROWS = 12          # image height (input feature per column)
COLS_PER = 4       # columns per digit glyph
LEN = 3            # digits per image
N_DIGIT = 5        # digit alphabet 0..4 -> ctc classes 1..5, blank=0
T = LEN * COLS_PER + 4   # total columns (blank margins)
N_CLASS = N_DIGIT + 1    # + blank


def make_batch(rng, batch):
    """Images (batch, T, ROWS) + labels (batch, LEN) in 1..N_DIGIT."""
    imgs = rng.uniform(0, 0.15, (batch, T, ROWS)).astype(np.float32)
    labels = np.zeros((batch, LEN), np.float32)
    for b in range(batch):
        digits = rng.randint(0, N_DIGIT, LEN)
        labels[b] = digits + 1  # 0 is the CTC blank
        col = 2
        for d in digits:
            h = 2 + 2 * d  # bar height encodes the digit
            imgs[b, col:col + COLS_PER - 1, :h] += rng.uniform(0.7, 1.0)
            col += COLS_PER
    return imgs, labels


def get_symbol(num_hidden=32):
    data = mx.sym.Variable("data")            # (B, T, ROWS)
    label = mx.sym.Variable("label")          # (B, LEN)
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(num_hidden=num_hidden, prefix="lstm_"))
    outputs, _ = stack.unroll(T, inputs=data, layout="NTC",
                              merge_outputs=True)
    # per-timestep class scores: (B,T,H) -> (B*T,H) -> FC -> (T,B,C)
    flat = mx.sym.reshape(outputs, shape=(-1, num_hidden))
    pred = mx.sym.FullyConnected(flat, num_hidden=N_CLASS, name="cls")
    pred = mx.sym.reshape(pred, shape=(-1, T, N_CLASS))
    pred = mx.sym.transpose(pred, axes=(1, 0, 2))  # (T,B,C)
    loss = mx.sym.MakeLoss(mx.sym.mean(
        mx.contrib.sym.ctc_loss(pred, label)), name="ctc")
    # raw (T,B,C) scores for greedy decoding (argmax over C is invariant
    # to the softmax, so no activation needed on the inference head)
    scores = mx.sym.BlockGrad(pred, name="scores")
    return mx.sym.Group([loss, scores])


def greedy_decode(post):
    """(T,B,C) posteriors -> list of label sequences (collapse repeats,
    drop blanks)."""
    ids = post.argmax(axis=2)  # (T,B)
    out = []
    for b in range(ids.shape[1]):
        seq, prev = [], -1
        for t in range(ids.shape[0]):
            c = int(ids[t, b])
            if c != prev and c != 0:
                seq.append(c)
            prev = c
        out.append(seq)
    return out


def train(num_epochs=12, batch=32, lr=0.005, seed=0, ctx=None, log=True,
          stop_acc=None):
    ctx = ctx or mx.cpu()
    rng = np.random.RandomState(seed)
    np.random.seed(seed + 1)  # initializer stream
    sym = get_symbol()
    ex = sym.simple_bind(ctx, data=(batch, T, ROWS), label=(batch, LEN),
                         grad_req={n: ("null" if n in ("data", "label")
                                       else "write")
                                   for n in sym.list_arguments()})
    init = mx.initializer.Xavier()
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "label"):
            init(mx.initializer.InitDesc(name), arr)
    opt = mx.optimizer.Adam(learning_rate=lr)
    updater = mx.optimizer.get_updater(opt)

    acc = 0.0
    for epoch in range(num_epochs):
        hits = tot = 0
        losses = []
        for _ in range(20):
            imgs, labels = make_batch(rng, batch)
            ex.arg_dict["data"][:] = imgs
            ex.arg_dict["label"][:] = labels
            ex.forward(is_train=True)
            ex.backward()
            for i, name in enumerate(sorted(ex.grad_dict)):
                g = ex.grad_dict[name]
                if g is not None:
                    updater(i, g, ex.arg_dict[name])
            outs = [o.asnumpy() for o in ex.outputs]
            losses.append(float(outs[0].mean()))
            decoded = greedy_decode(outs[1])
            want = [list(map(int, row)) for row in labels]
            hits += sum(1 for d, w in zip(decoded, want) if d == w)
            tot += batch
        acc = hits / tot
        if log:
            logging.info("epoch %d: ctc_loss=%.3f seq_acc=%.3f",
                         epoch, float(np.mean(losses)), acc)
        if stop_acc is not None and acc >= stop_acc:
            break
    return {"seq_acc": acc}


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(description="CTC OCR training")
    p.add_argument("--num-epochs", type=int, default=12)
    p.add_argument("--tpus", type=int, default=0)
    args = p.parse_args()
    ctx = mx.tpu(0) if args.tpus else mx.cpu()
    stats = train(num_epochs=args.num_epochs, ctx=ctx)
    print("final:", stats)
    assert stats["seq_acc"] > 0.8, stats


if __name__ == "__main__":
    main()
