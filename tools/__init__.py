"""Developer tooling (launchers, benchmarks, docs generation, and the
``tools.graftcheck`` static-analysis suite).  Scripts here are run
directly (``python tools/launch.py``) or as modules
(``python -m tools.graftcheck``)."""
