"""Attribute scoping (parity: reference ``python/mxnet/attribute.py``).

``AttrScope`` carries string attributes (notably ``ctx_group`` for model
parallelism and ``__shard__`` for GSPMD sharding specs — the TPU-native
extension) onto symbols created inside the scope.
"""

from __future__ import annotations

__all__ = ["AttrScope"]


class AttrScope:
    """Attribute manager for scoping; attrs apply to symbols created within."""

    current = None

    def __init__(self, **kwargs):
        self._old_scope = None
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("Attributes need to be a string")
        self._attr = kwargs

    def get(self, attr):
        """Merge scope attrs with user attrs (user wins)."""
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        self._old_scope = AttrScope.current
        attr = AttrScope.current._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope.current = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_scope
        AttrScope.current = self._old_scope


AttrScope.current = AttrScope()
