"""RecordIO — record-packed dataset container (parity: reference
``python/mxnet/recordio.py`` + dmlc-core recordio).

Binary format is kept compatible with the reference: records framed with the
dmlc magic ``0xced7230a`` + length word (upper 3 bits = continuation flag),
payloads padded to 4 bytes; ``IRHeader`` packs (flag, label, id, id2) with
``struct '<IfQQ'`` exactly as ``recordio.py:19-168``.  Sequential read and
all writes go through the native C++ backend (``native/src/recordio.cc``)
when built — the dmlc-core recordio role; indexed random access stays in
Python.  Set ``MXTPU_NO_NATIVE=1`` to force pure Python.
"""

from __future__ import annotations

import ctypes
import numbers
import os
import struct
from collections import namedtuple

import numpy as np

from . import _native

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_FORCE_PYTHON = False  # test hook: force the pure-Python backend

_MAGIC = 0xCED7230A
_LREC_KIND_BITS = 29


def _encode_lrec(cflag, length):
    return (cflag << _LREC_KIND_BITS) | length


def _decode_lrec(rec):
    return (rec >> _LREC_KIND_BITS) & 7, rec & ((1 << _LREC_KIND_BITS) - 1)


class MXRecordIO(object):
    """Sequential RecordIO reader/writer (parity: ``recordio.py:MXRecordIO``)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.open()

    def open(self):
        self._nh = None
        self._nlib = None if _FORCE_PYTHON else _native.lib()
        if self.flag == "w":
            if self._nlib is not None:
                self._nh = self._nlib.mxtpu_recordio_writer_open(
                    self.uri.encode())
            self.handle = None if self._nh else open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            # native reader is sequential-only; subclasses needing seek()
            # (MXIndexedRecordIO) stay on the Python file handle
            if self._nlib is not None and type(self) is MXRecordIO:
                self._nh = self._nlib.mxtpu_recordio_reader_open(
                    self.uri.encode())
            self.handle = None if self._nh else open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)

    def close(self):
        if getattr(self, "_nh", None):
            if self.writable:
                self._nlib.mxtpu_recordio_writer_close(self._nh)
            else:
                self._nlib.mxtpu_recordio_reader_close(self._nh)
            self._nh = None
        if self.handle is not None:
            self.handle.close()
            self.handle = None

    def __del__(self):
        self.close()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        if self._nh:
            if self.writable:
                return self._nlib.mxtpu_recordio_writer_tell(self._nh)
            return self._nlib.mxtpu_recordio_reader_tell(self._nh)
        return self.handle.tell()

    def write(self, buf):
        assert self.writable
        buf = bytes(buf)  # accept bytearray/memoryview on both backends
        if len(buf) >= 1 << _LREC_KIND_BITS:
            raise ValueError("record too large for RecordIO framing "
                             "(%d >= 2^29 bytes)" % len(buf))
        if self._nh:
            if self._nlib.mxtpu_recordio_writer_write(
                    self._nh, buf, len(buf)) != 0:
                raise IOError("native recordio write failed")
            return
        self.handle.write(struct.pack("<II", _MAGIC, _encode_lrec(0, len(buf))))
        self.handle.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        if self._nh:
            out = ctypes.POINTER(ctypes.c_char)()
            n = ctypes.c_size_t()
            r = self._nlib.mxtpu_recordio_reader_next(
                self._nh, ctypes.byref(out), ctypes.byref(n))
            if r == 1:
                return _native.buf_to_bytes(self._nlib, out, n.value)
            if r == 0:
                return None
            raise IOError("Invalid RecordIO magic number")
        # reassemble continuation-framed records (kind 0 = whole record,
        # 1 = first part, 2 = middle, 3 = last) like the native reader
        parts = []
        while True:
            header = self.handle.read(8)
            if len(header) < 8:
                # clean EOF is exactly 0 bytes at a record boundary with no
                # continuation pending; anything else is a corrupt stream
                # (the native reader errors here too) — returning a partial
                # join / None would be silent data corruption
                if parts:
                    raise IOError("truncated multi-part RecordIO record "
                                  "at EOF")
                if header:
                    raise IOError("truncated RecordIO header at EOF "
                                  "(%d of 8 bytes)" % len(header))
                return None
            magic, lrec = struct.unpack("<II", header)
            if magic != _MAGIC:
                raise IOError("Invalid RecordIO magic number")
            kind, length = _decode_lrec(lrec)
            payload = self.handle.read(length)
            if len(payload) < length:
                raise IOError("truncated RecordIO payload "
                              "(%d < %d bytes)" % (len(payload), length))
            parts.append(payload)
            pad = (4 - length % 4) % 4
            if pad:
                self.handle.read(pad)
            if kind == 0 or kind == 3:
                return b"".join(parts)


class MXIndexedRecordIO(MXRecordIO):
    """Indexed RecordIO with ``.idx`` sidecar (parity:
    ``recordio.py:MXIndexedRecordIO``)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            with open(idx_path) as fin:
                for line in fin.readlines():
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)

    def close(self):
        is_open = self.handle is not None or getattr(self, "_nh", None)
        if is_open and self.writable:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write("%s\t%d\n" % (str(key), self.idx[key]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.keys.append(key)
        self.idx[key] = pos


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a header + bytes into a record payload (parity: ``recordio.py:pack``)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    s = struct.pack(_IR_FORMAT, *header) + s
    return s


def unpack(s):
    """Unpack a record payload (parity: ``recordio.py:unpack``)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        header = header._replace(
            label=np.frombuffer(s[: header.flag * 4], dtype=np.float32))
        s = s[header.flag * 4 :]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array (parity: ``recordio.py:pack_img``; PNG/raw-npy
    encoding here since OpenCV isn't a dependency)."""
    from .image import imencode

    return pack(header, imencode(img, img_fmt=img_fmt, quality=quality))


def unpack_img(s, iscolor=-1):
    header, s = unpack(s)
    from .image import imdecode_bytes

    img = imdecode_bytes(s)
    return header, img
