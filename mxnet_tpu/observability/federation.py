"""Cluster metrics federation: scrape every shard, render one view.

The pull-based federation model (Prometheus/Borgmon): each
``AsyncServer`` / standby / worker process serves its own ``/metrics``
endpoint (``exporters.start_metrics_server``; the port travels with
``publish_address``), and a :class:`FederatedCollector` scrapes them
all and re-renders a single cluster-wide exposition in which every
series carries ``shard`` / ``role`` / ``epoch`` labels identifying the
member it came from.

A federation **target** is a dict with the three identity labels plus
exactly one source::

    {"shard": 0, "role": "primary", "epoch": 1,
     "url": "http://127.0.0.1:9100/metrics"}   # scrape over HTTP
    {"shard": 0, "role": "standby", "epoch": 1,
     "registry": obs.REGISTRY}                 # in-process registry
    {"shard": 1, "role": "primary", "epoch": 0,
     "text": "...exposition..."}               # pre-scraped text

Sources are deduplicated **by identity**: the in-process test layout
runs every server thread against ONE process-global registry, so
listing that registry once per member would repeat process-wide
counters (``kv_failover_total``) once per row.  A shared source is
scraped and emitted exactly once — under the labels of the first
member naming it — while every member still contributes its
``cluster_server_info{shard,role,epoch} 1`` identity series, keeping
failover/fence counters exactly-once in the federated view.

On top of the relabeled series the collector derives cluster health:
``cluster_replication_lag_max`` (max follower seqno delta anywhere),
``cluster_heartbeat_age_max_seconds``, summed
``cluster_failover_total`` / ``cluster_fenced_total``, and
``cluster_scrape_errors_total`` (unreachable members, labeled by
shard).  With ``MXNET_TPU_METRICS=0`` :meth:`FederatedCollector.render`
returns an empty exposition WITHOUT scraping anything — a constant-time
guard like every other handle.
"""

from __future__ import annotations

import json
import os
import re
import time
import urllib.request

from . import metrics as _metrics

__all__ = ["FederatedCollector", "federate"]

_IDENTITY = ("shard", "role", "epoch")

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

#: Server-side ops whose ``kv_serve_seconds`` latency counts toward a
#: shard's straggler score (the data plane; heartbeats/replication and
#: control ops would mask a slow shard behind cheap chatter).
_DATA_OPS = frozenset({"push", "pull", "init"})


def _label_dict(labelbody):
    """``'a="x",b="y"'`` → ``{"a": "x", "b": "y"}`` (tolerant: unparsed
    fragments are dropped)."""
    return dict(_LABEL_RE.findall(labelbody))


def _skew_threshold():
    """Max/min latency ratio past which the slowest member is named in
    ``cluster_straggler_info`` (``MXNET_TPU_WATCHDOG_STRAGGLER_SKEW``)."""
    try:
        return float(os.environ.get("MXNET_TPU_WATCHDOG_STRAGGLER_SKEW",
                                    "2.0"))
    except ValueError:
        return 2.0


def _scrape_one(target, timeout):
    """Raw exposition text from one target's source.  Module-level so
    tests can monkeypatch it to count calls on the disabled path."""
    if "text" in target:
        return target["text"]
    if "registry" in target:
        return target["registry"].render()
    req = urllib.request.Request(target["url"])
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read().decode("utf-8")


def _scrape_events(target, timeout):
    """JSON-lines ops-event text from one target.  URL targets answer
    from their ``/events`` endpoint (derived from the metrics URL);
    in-process targets (registry/text) read the process-global event
    ring directly.  Module-level seam for disabled-path call counting."""
    if "url" in target:
        url = target["url"]
        if url.endswith("/metrics"):
            url = url[:-len("/metrics")] + "/events"
        req = urllib.request.Request(url)
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode("utf-8")
    from .events import render_jsonl
    return render_jsonl()


def _source_key(target):
    """Identity of the underlying source, for exactly-once dedup."""
    if "text" in target:
        return ("text", id(target["text"]))
    if "registry" in target:
        return ("registry", id(target["registry"]))
    if "url" in target:
        return ("url", target["url"])
    raise ValueError("federation target needs one of url/registry/text: %r"
                     % (sorted(target),))


def _parse(text):
    """Exposition text → ordered ``{family: {help, type, series}}``
    where ``series`` is ``[(name, labelbody_or_None, value_str)]``.
    Tolerant: lines that don't parse are skipped (a half-written peer
    exposition must not take down the federated view)."""
    fams = {}
    cur = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            try:
                _, kw, name, rest = line.split(" ", 3)
            except ValueError:
                continue
            fam = fams.setdefault(name, {"help": "", "type": "untyped",
                                         "series": []})
            fam["help" if kw == "HELP" else "type"] = rest
            cur = name
            continue
        if line.startswith("#"):
            continue
        # OpenMetrics exemplar annotation (metrics.Registry.render with
        # exemplars=True): everything after " # {" is not the sample
        if " # {" in line:
            line = line.split(" # {", 1)[0].rstrip()
        try:
            series_id, value = line.rsplit(" ", 1)
        except ValueError:
            continue
        if "{" in series_id:
            name, rest = series_id.split("{", 1)
            if not rest.endswith("}"):
                continue
            labels = rest[:-1]
        else:
            name, labels = series_id, None
        if not _NAME_RE.match(name):
            continue
        fam_name = cur if (cur and name.startswith(cur)) else name
        fam = fams.setdefault(fam_name, {"help": "", "type": "untyped",
                                         "series": []})
        fam["series"].append((name, labels, value))
    return fams


def _identity_pairs(target):
    return ",".join('%s="%s"' % (k, _metrics._fmt_label(target.get(k, "")))
                    for k in _IDENTITY)


def _relabel(name, labels, ident):
    body = ident if not labels else labels + "," + ident
    return "%s{%s}" % (name, body)


class FederatedCollector(object):
    """Scrape a list of federation targets and render one cluster-wide
    exposition.  Has a ``render()`` method, so it can be served
    directly: ``start_metrics_server(registry=collector)``."""

    def __init__(self, targets, timeout=2.0):
        self.targets = list(targets)
        self.timeout = timeout
        # (monotonic, total kv wire bytes) of the previous render pass —
        # the finite difference behind cluster_wire_mb_per_sec
        self._last_wire = None
        for t in self.targets:
            _source_key(t)   # validate eagerly

    def render(self):
        if not _metrics.metrics_enabled():
            return ""
        out = []
        w = out.append
        w("# HELP cluster_server_info Federation membership "
          "(one series per member)\n")
        w("# TYPE cluster_server_info gauge\n")
        for t in self.targets:
            w("cluster_server_info{%s} 1\n" % _identity_pairs(t))

        merged = {}          # family -> {help, type, lines: [...]}
        values = {}          # bare series name -> [float] across members
        errors = []          # identity pair strings of failed scrapes
        seen = {}            # source key -> True
        serve = {}           # server label -> [sum_s, count] (data ops)
        wsteps = {}          # member name -> [sum_s, count] (worker steps)
        mfu = {}             # member name -> model_flops_utilization
        wire = {}            # (member, dir) -> kv wire bytes
        mempool = {}         # (member, pool) -> memory_pool_bytes
        headroom = {}        # member name -> min memory_headroom_ratio
        for t in self.targets:
            key = _source_key(t)
            if key in seen:
                continue
            seen[key] = True
            try:
                text = _scrape_one(t, self.timeout)
            except Exception:
                errors.append(_identity_pairs(t))
                continue
            ident = _identity_pairs(t)
            member = "%s:%s:%s" % (t.get("shard", ""), t.get("role", ""),
                                   t.get("epoch", ""))
            for fam_name, fam in _parse(text).items():
                slot = merged.setdefault(
                    fam_name, {"help": fam["help"], "type": fam["type"],
                               "lines": []})
                if slot["type"] == "untyped" and fam["type"] != "untyped":
                    slot["help"], slot["type"] = fam["help"], fam["type"]
                for name, labels, value in fam["series"]:
                    slot["lines"].append(
                        "%s %s\n" % (_relabel(name, labels, ident), value))
                    try:
                        fval = float(value)
                    except ValueError:
                        continue
                    values.setdefault(name, []).append(fval)
                    # straggler inputs: per-shard serve latency (the
                    # server label distinguishes shards even when an
                    # in-process layout shares one registry) and
                    # per-member worker step latency
                    if name in ("kv_serve_seconds_sum",
                                "kv_serve_seconds_count"):
                        ld = _label_dict(labels or "")
                        if ld.get("op") in _DATA_OPS:
                            acc = serve.setdefault(ld.get("server", "?"),
                                                   [0.0, 0.0])
                            acc[0 if name.endswith("_sum") else 1] += fval
                    elif name in ("trainer_step_seconds_sum",
                                  "trainer_step_seconds_count"):
                        acc = wsteps.setdefault(member, [0.0, 0.0])
                        acc[0 if name.endswith("_sum") else 1] += fval
                    elif name == "model_flops_utilization" and fval > 0:
                        # zero = a lazily-registered gauge that never
                        # measured; it must not drag cluster_mfu_min
                        mfu[member] = fval
                    elif name == "kv_wire_bytes_total":
                        # byte books per member+direction (header and
                        # payload parts collapse — the federation view
                        # answers 'how much', the local one 'of what')
                        ld = _label_dict(labels or "")
                        k = (member, ld.get("dir", "?"))
                        wire[k] = wire.get(k, 0.0) + fval
                    elif name == "memory_pool_bytes":
                        # capacity books per member+pool (device rows
                        # collapse — the federation view answers 'how
                        # much', the local one 'where')
                        ld = _label_dict(labels or "")
                        k = (member, ld.get("pool", "?"))
                        mempool[k] = mempool.get(k, 0.0) + fval
                    elif name == "memory_headroom_ratio" and fval > 0:
                        # zero = a reset placeholder that never sampled;
                        # it must not drag cluster_memory_headroom_min
                        headroom[member] = min(
                            headroom.get(member, float("inf")), fval)

        # families sorted by name; series keep scrape order (histogram
        # buckets must stay in ascending-le order, which lexical
        # sorting would scramble) — deterministic because both the
        # target list and each registry's render are
        for fam_name in sorted(merged):
            slot = merged[fam_name]
            if slot["help"]:
                w("# HELP %s %s\n" % (fam_name, slot["help"]))
            w("# TYPE %s %s\n" % (fam_name, slot["type"]))
            for line in slot["lines"]:
                w(line)

        def derived(name, help, kind, value):
            w("# HELP %s %s\n" % (name, help))
            w("# TYPE %s %s\n" % (name, kind))
            w("%s %s\n" % (name, _metrics._fmt_value(value)))

        derived("cluster_replication_lag_max",
                "Max follower seqno delta across all members", "gauge",
                max(values.get("kv_replication_lag", [0]) or [0]))
        derived("cluster_heartbeat_age_max_seconds",
                "Max heartbeat age across all members", "gauge",
                max(values.get("kv_heartbeat_age_seconds", [0]) or [0]))
        derived("cluster_failover_total",
                "Failovers summed across all members", "counter",
                sum(values.get("kv_failover_total", [])))
        derived("cluster_fenced_total",
                "Fenced primaries summed across all members", "counter",
                sum(values.get("kv_fenced_total", [])))

        # -- straggler detection: per-shard / per-worker mean latency,
        # the skew ratio, and a row NAMING the slowest member when the
        # skew crosses the threshold -----------------------------------
        shard_lat = {k: s / c for k, (s, c) in serve.items() if c}
        worker_lat = {k: s / c for k, (s, c) in wsteps.items() if c}
        if shard_lat:
            w("# HELP cluster_shard_serve_seconds Mean data-plane serve "
              "latency per shard (push/pull/init)\n")
            w("# TYPE cluster_shard_serve_seconds gauge\n")
            for k in sorted(shard_lat):
                w('cluster_shard_serve_seconds{server="%s"} %s\n'
                  % (_metrics._fmt_label(k),
                     _metrics._fmt_value(shard_lat[k])))
        if worker_lat:
            w("# HELP cluster_step_latency_seconds Mean trainer step "
              "latency per federation member\n")
            w("# TYPE cluster_step_latency_seconds gauge\n")
            for k in sorted(worker_lat):
                w('cluster_step_latency_seconds{member="%s"} %s\n'
                  % (_metrics._fmt_label(k),
                     _metrics._fmt_value(worker_lat[k])))
        skews = []           # (kind, skew, slowest member)
        for kind, lat in (("shard", shard_lat), ("worker", worker_lat)):
            if len(lat) < 2:
                continue
            slowest = max(lat, key=lat.get)
            floor = max(min(lat.values()), 1e-12)
            skews.append((kind, lat[slowest] / floor, slowest))
        if skews:
            w("# HELP cluster_straggler_skew Slowest/fastest mean-latency "
              "ratio across members of one kind\n")
            w("# TYPE cluster_straggler_skew gauge\n")
            for kind, skew, _ in skews:
                w('cluster_straggler_skew{kind="%s"} %s\n'
                  % (kind, _metrics._fmt_value(skew)))
        threshold = _skew_threshold()
        stragglers = [(kind, skew, who) for kind, skew, who in skews
                      if skew >= threshold]
        if stragglers:
            w("# HELP cluster_straggler_info The slowest member of each "
              "kind whose skew exceeds the threshold\n")
            w("# TYPE cluster_straggler_info gauge\n")
            for kind, skew, who in stragglers:
                w('cluster_straggler_info{kind="%s",member="%s"} 1\n'
                  % (kind, _metrics._fmt_label(who)))

        # -- hardware efficiency: per-member MFU plus the fleet floor
        # (the member every efficiency regression hunt starts from) ----
        if mfu:
            w("# HELP cluster_mfu Model FLOPs utilization per federation "
              "member (model_flops_utilization)\n")
            w("# TYPE cluster_mfu gauge\n")
            for k in sorted(mfu):
                w('cluster_mfu{member="%s"} %s\n'
                  % (_metrics._fmt_label(k), _metrics._fmt_value(mfu[k])))
            w("# HELP cluster_mfu_min The least-utilized member's MFU — "
              "the fleet's efficiency floor\n")
            w("# TYPE cluster_mfu_min gauge\n")
            w("cluster_mfu_min %s\n"
              % _metrics._fmt_value(min(mfu.values())))

        # -- memory capacity: per-member pool books plus the fleet's
        # headroom floor (the member the next OOM hunts start from) ----
        if mempool:
            w("# HELP cluster_memory_pool_bytes Memory-ledger pool bytes "
              "per federation member (device rows summed from "
              "memory_pool_bytes)\n")
            w("# TYPE cluster_memory_pool_bytes gauge\n")
            for member, pool in sorted(mempool):
                w('cluster_memory_pool_bytes{member="%s",pool="%s"} %s\n'
                  % (_metrics._fmt_label(member), _metrics._fmt_label(pool),
                     _metrics._fmt_value(mempool[(member, pool)])))
        if headroom:
            w("# HELP cluster_memory_headroom_min The tightest device "
              "memory headroom ratio across all members — the fleet's "
              "OOM-proximity floor\n")
            w("# TYPE cluster_memory_headroom_min gauge\n")
            w("cluster_memory_headroom_min %s\n"
              % _metrics._fmt_value(min(headroom.values())))

        # -- wire bandwidth: per-member byte books plus a cluster-wide
        # MB/s rate from the delta against the previous render pass ----
        if wire:
            w("# HELP cluster_kv_wire_bytes Kvstore wire bytes per "
              "federation member and direction (header+payload summed "
              "from kv_wire_bytes_total)\n")
            w("# TYPE cluster_kv_wire_bytes gauge\n")
            for member, dirn in sorted(wire):
                w('cluster_kv_wire_bytes{member="%s",dir="%s"} %s\n'
                  % (_metrics._fmt_label(member), _metrics._fmt_label(dirn),
                     _metrics._fmt_value(wire[(member, dirn)])))
        wire_total = sum(wire.values())
        now = time.monotonic()
        rate = 0.0
        if self._last_wire is not None:
            t_prev, b_prev = self._last_wire
            dt = now - t_prev
            if dt > 0 and wire_total >= b_prev:
                rate = (wire_total - b_prev) / dt / (1 << 20)
        self._last_wire = (now, wire_total)
        derived("cluster_wire_mb_per_sec",
                "Cluster-wide kvstore wire bandwidth (MiB/s) since the "
                "previous federation pass (0 on the first pass)",
                "gauge", rate)

        w("# HELP cluster_scrape_errors_total Members whose source "
          "could not be scraped this pass\n")
        w("# TYPE cluster_scrape_errors_total counter\n")
        w("cluster_scrape_errors_total %d\n" % len(errors))
        for ident in errors:
            w("cluster_scrape_errors_total{%s} 1\n" % ident)
        return "".join(out)

    def render_events(self):
        """Every member's structured ops event ring merged into ONE
        JSON-lines log, each line annotated with the member's identity
        labels and sorted by wall time across members.  In-process
        targets (registry/text sources) all read the same
        process-global ring, so it contributes exactly once — under
        the first member naming it — mirroring the metrics dedup.
        Unreachable members are skipped (a half-dead cluster must
        still yield its surviving members' history)."""
        if not _metrics.metrics_enabled():
            return ""
        rows = []
        seen = set()
        for t in self.targets:
            # unlike metrics registries, the event ring is per-PROCESS:
            # every non-url target collapses to one local source
            key = ("url", t["url"]) if "url" in t else ("local",)
            if key in seen:
                continue
            seen.add(key)
            try:
                text = _scrape_events(t, self.timeout)
            except Exception:
                continue
            for line in text.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                for k in _IDENTITY:
                    # identity rides as label-style strings, same as
                    # the relabeled metrics exposition
                    ev[k] = str(t.get(k, ""))
                rows.append(ev)
        rows.sort(key=lambda e: (e.get("time_unix", 0) or 0,
                                 e.get("pid", 0) or 0,
                                 e.get("seq", 0) or 0))
        return "".join(json.dumps(e, sort_keys=True) + "\n"
                       for e in rows)


def federate(targets, timeout=2.0):
    """One-shot federation: scrape ``targets`` and return the
    cluster-wide exposition text (see :class:`FederatedCollector`)."""
    return FederatedCollector(targets, timeout=timeout).render()
