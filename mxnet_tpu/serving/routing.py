"""KV-affinity routing: keep a generation session on its cache.

Round-robin (:class:`~.replication.ServingRouter`) is the right call
for stateless classification, but a *generation session* leaves state
behind: its :class:`~.paged_kv.PagedKVCache` blocks live on whichever
replica ran the prefill.  Routing the session's next request anywhere
else forfeits that work — the peer must re-prefill the whole prompt.
:class:`KVAffinityRouter` therefore pins each session to a **home
replica** and keeps sending it there, spilling only when staying home
is worse than re-prefilling:

- **hit** — the session routes to its home replica; decode resumes on
  warm KV blocks.
- **spill** — the home replica's load exceeds
  ``MXNET_TPU_ROUTE_SPILL_FACTOR`` × the least-loaded peer's, so the
  session moves and re-prefills there.  A spilled generation is
  bitwise-equivalent to a cold session (deterministic prefill+decode);
  only latency is paid.
- **dead** — the home replica is fenced or unroutable; the session
  re-homes on a live peer (re-prefill, nothing dropped).
- **miss** — first request of a session (or affinity disabled): pick
  the least-loaded routable replica, round-robin among ties.
- **failover** — a replica died *holding* an accepted generation; the
  group fences it and the request is re-admitted on a peer with
  ``force=True`` and the remaining deadline — the PR-8 brownout
  contract (accepted work is never dropped) extended to affinity
  misses.

Every candidate replica passes the ``serving.route`` chaos site first
(name ``<model>:<replica index>``): a fired ``raise``/``drop`` rule
makes that replica unroutable for the attempt — the deterministic way
to drill spills and re-homes — while ``delay`` stretches routing.

Outcomes are accounted in ``serving_route_total{group, outcome}``;
``kv_affinity_hit_ratio{group}`` is hits over lookups, where a lookup
is counted **only when the session already had a placement** — a fresh
session's unavoidable miss never dilutes the ratio.

Scale events need no router surgery: :meth:`~.replication.
ReplicaGroup.grow` replicas join the candidate set on the next route,
and a shrink's drain refuses new admits, which reads as *dead* here
and re-homes the session.
"""

from __future__ import annotations

import os
import threading
import time

from .. import chaos as _chaos
from ..observability import metrics as _metrics
from ..observability.events import emit as _emit_event
from . import admission as _admission

__all__ = ["KVAffinityRouter", "default_affinity", "default_spill_factor"]

_M_ROUTE = _metrics.counter(
    "serving_route_total",
    "Affinity-router decisions, by outcome "
    "(hit | miss | spill | dead | failover)",
    ["group", "outcome"])
_M_HIT_RATIO = _metrics.gauge(
    "kv_affinity_hit_ratio",
    "Sessions routed onto their existing KV blocks, over routed "
    "sessions that had any prior placement", ["group"])


def default_affinity():
    """``MXNET_TPU_ROUTE_AFFINITY`` — set 0 to fall back to pure
    least-loaded routing (every request re-prefills)."""
    raw = os.environ.get("MXNET_TPU_ROUTE_AFFINITY", "1")
    return raw.strip().lower() not in ("0", "false", "off")


def default_spill_factor():
    """``MXNET_TPU_ROUTE_SPILL_FACTOR`` — spill a session off its home
    replica when home load exceeds this factor × the least-loaded
    peer's (default 4: staying on warm KV is worth a 4× queue)."""
    try:
        factor = float(os.environ.get("MXNET_TPU_ROUTE_SPILL_FACTOR", "4"))
    except ValueError:
        factor = 4.0
    return factor if factor > 0 else 4.0


class KVAffinityRouter(object):
    """Session-sticky router over a :class:`~.replication.ReplicaGroup`
    of :class:`~.generation.GenerationScheduler` replicas (built with
    ``scheduler_cls=GenerationScheduler``).

    ``session`` is the caller's opaque session id (a conversation, a
    user stream); requests without one are routed least-loaded like any
    stateless call.  The router never *drops* on a routing fault — the
    worst case is a re-prefill somewhere alive.
    """

    def __init__(self, group, affinity=None, spill_factor=None):
        self._group = group
        self._affinity = (default_affinity() if affinity is None
                          else bool(affinity))
        self._spill = (default_spill_factor() if spill_factor is None
                       else float(spill_factor))
        self._lock = threading.Lock()
        self._rr = 0
        self._sessions = {}      # session id -> home replica index
        self._hits = 0
        self._lookups = 0

    # -- placement ----------------------------------------------------

    def _routable(self, model):
        """Live replicas that survive the ``serving.route`` chaos gate
        this attempt.  A fired rule only skips the one candidate — the
        route falls through to peers, never to a drop."""
        out = []
        for index, sched in self._group.live():
            try:
                _chaos.visit("serving.route",
                             name="%s:%d" % (model, index))
            except _chaos.ChaosError:
                continue
            out.append((index, sched))
        return out

    def _account(self, outcome, lookup=False, hit=False):
        with self._lock:
            if lookup:
                self._lookups += 1
                if hit:
                    self._hits += 1
            hits, lookups = self._hits, self._lookups
        if _metrics.metrics_enabled():
            _M_ROUTE.labels(self._group.group, outcome).inc()
            if lookups:
                _M_HIT_RATIO.labels(self._group.group).set(hits / lookups)

    def _forget_replica(self, index):
        """Drop every session homed on a fenced replica — its KV blocks
        died with it, so the next request is an honest miss."""
        with self._lock:
            dead = [s for s, i in self._sessions.items() if i == index]
            for s in dead:
                del self._sessions[s]

    def route(self, model, session=None):
        """Pick ``(index, scheduler)`` for one request, accounting the
        outcome.  Raises :class:`~.admission.ReplicaDeadError` when no
        replica is routable at all."""
        cands = self._routable(model)
        attempts = 0
        while not cands:
            # every candidate got chaos-blocked this pass; while live
            # replicas exist that is transient unroutability, not
            # death — re-roll the gate (bounded, so a prob=1 rule
            # still surfaces as dead instead of spinning)
            if not self._group.live() or attempts >= 16:
                raise _admission.ReplicaDeadError(
                    "group %r has no routable serving replica"
                    % self._group.group)
            attempts += 1
            cands = self._routable(model)
        by_index = dict(cands)
        home = None
        if session is not None and self._affinity:
            with self._lock:
                home = self._sessions.get(session)
        if home is not None:
            if home in by_index:
                loads = {i: s.load() for i, s in cands}
                peer_min = min((l for i, l in loads.items() if i != home),
                               default=None)
                # +1 keeps an idle group from thrashing: a home queue of
                # 1 vs empty peers is not worth forfeiting warm KV
                if (peer_min is not None
                        and loads[home] > self._spill * (peer_min + 1)):
                    choice = min((i for i in loads if i != home),
                                 key=loads.get)
                    self._place(session, choice)
                    self._account("spill", lookup=True)
                    _emit_event("serving.route", group=self._group.group,
                                 model=model, outcome="spill",
                                 session=str(session), replica=choice)
                    return choice, by_index[choice]
                self._account("hit", lookup=True, hit=True)
                return home, by_index[home]
            # home fenced or chaos-blocked: re-home (the mapping is
            # only forgotten when the replica is actually gone —
            # _forget_replica on fence — so a chaos blip re-homes
            # without poisoning a healthy map)
            choice = self._least_loaded(cands)
            self._place(session, choice)
            self._account("dead", lookup=True)
            _emit_event("serving.route", group=self._group.group,
                         model=model, outcome="dead",
                         session=str(session), replica=choice)
            return choice, by_index[choice]
        choice = self._least_loaded(cands)
        if session is not None and self._affinity:
            self._place(session, choice)
        self._account("miss")
        return choice, by_index[choice]

    def _least_loaded(self, cands):
        with self._lock:
            start = self._rr
            self._rr += 1
        order = (cands[start % len(cands):] + cands[:start % len(cands)])
        return min(order, key=lambda t: t[1].load())[0]

    def _place(self, session, index):
        if session is not None:
            with self._lock:
                self._sessions[session] = index

    def placement(self, session):
        """The session's current home replica index, or None."""
        with self._lock:
            return self._sessions.get(session)

    def end_session(self, session):
        """Forget a finished session's placement."""
        with self._lock:
            self._sessions.pop(session, None)

    # -- request paths ------------------------------------------------

    @staticmethod
    def _remaining_ms(req):
        if req.deadline is None:
            return 0  # deadline_from_ms(0) -> no deadline
        return max((req.deadline - time.monotonic()) * 1e3, 0.001)

    def submit(self, model, prompt, max_new_tokens=None, eos_id=None,
               deadline_ms=None, tenant=None, session=None, force=False):
        """Route + admit one generation; returns ``(request, index)``.
        A replica found dead at the door is fenced and the route
        retried; sheds (overload / drain / quota) surface to the caller
        untouched — peers would only multiply a tenant's quota."""
        while True:
            index, sched = self.route(model, session=session)
            try:
                req = sched.submit(model, prompt,
                                   max_new_tokens=max_new_tokens,
                                   eos_id=eos_id, deadline_ms=deadline_ms,
                                   tenant=tenant, force=force)
            except _admission.ReplicaDeadError:
                self._group.fence(index)
                self._forget_replica(index)
                continue
            return req, index

    def generate(self, model, prompt, max_new_tokens=None, eos_id=None,
                 deadline_ms=None, timeout=60.0, tenant=None,
                 session=None):
        """Synchronous generation with failover: a replica that dies
        *holding* the accepted request is fenced and the generation
        re-admitted on a peer — ``force=True``, remaining deadline,
        full re-prefill — so accepted work is never dropped."""
        req, index = self.submit(model, prompt,
                                 max_new_tokens=max_new_tokens,
                                 eos_id=eos_id, deadline_ms=deadline_ms,
                                 tenant=tenant, session=session)
        try:
            return req.result(timeout=timeout)
        except _admission.ReplicaDeadError:
            self._group.fence(index)
            self._forget_replica(index)
            self._account("failover")
            _emit_event("serving.route", group=self._group.group,
                         model=model, outcome="failover",
                         session=str(session), replica=index)
            retry, _ = self.submit(model, prompt,
                                   max_new_tokens=max_new_tokens,
                                   eos_id=eos_id,
                                   deadline_ms=self._remaining_ms(req),
                                   tenant=req.tenant, session=session,
                                   force=True)
            return retry.result(timeout=timeout)
