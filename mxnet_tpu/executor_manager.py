"""Legacy data-parallel executor manager (parity: reference
``python/mxnet/executor_manager.py`` — ``DataParallelExecutorManager``, the
pre-Module multi-device training helper used by ``FeedForward``).

The reference hand-splits batches across device executors
(``_split_input_slice``) and scatter/gathers grads; here the same API is a
thin shim over one GSPMD-bound :class:`~mxnet_tpu.module.Module`, which
shards the batch across the context list on a mesh — per-device slicing is
the compiler's job.  Kept for API compatibility with FeedForward-era
training loops.
"""

from __future__ import annotations

import logging

__all__ = ["DataParallelExecutorManager", "_split_input_slice"]


def _split_input_slice(batch_size, work_load_list):
    """Slice a batch by device workload (parity:
    ``executor_manager.py:_split_input_slice``)."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        end = batch_size if i == len(work_load_list) - 1 else \
            start + int(round(batch_size * w / total))
        if end <= start:
            raise ValueError("Too many slices: batch %d over %d workers"
                             % (batch_size, len(work_load_list)))
        slices.append(slice(start, end))
        start = end
    return slices


class DataParallelExecutorManager(object):
    """(parity: ``executor_manager.py:DataParallelExecutorManager``)"""

    def __init__(self, symbol, ctx, train_data, arg_names=None,
                 param_names=None, aux_names=None, work_load_list=None,
                 logger=logging, sym_gen=None):
        from .module import Module

        if sym_gen is not None:
            raise NotImplementedError(
                "sym_gen (bucketing) is not supported by this shim; use "
                "mx.mod.BucketingModule")
        data_names = [d.name if hasattr(d, "name") else d[0]
                      for d in train_data.provide_data]
        label_names = [l.name if hasattr(l, "name") else l[0]
                       for l in (train_data.provide_label or [])]
        self._module = Module(symbol, data_names=data_names,
                              label_names=label_names, context=ctx,
                              work_load_list=work_load_list, logger=logger)
        self._module.bind(data_shapes=train_data.provide_data,
                          label_shapes=train_data.provide_label,
                          for_training=True)
        self.symbol = symbol
        self.ctx = ctx

    # -- reference surface ---------------------------------------------
    def install_monitor(self, monitor):
        self._module.install_monitor(monitor)

    def set_params(self, arg_params, aux_params):
        self._module.set_params(arg_params, aux_params,
                                allow_missing=False)

    def copy_to(self, arg_params, aux_params):
        """Copy current params into the given dicts (parity: ``copy_to``)."""
        args, auxs = self._module.get_params()
        for name, arr in args.items():
            if name in arg_params:
                arr.copyto(arg_params[name])
            else:
                arg_params[name] = arr.copy()
        for name, arr in auxs.items():
            if name in aux_params:
                arr.copyto(aux_params[name])
            else:
                aux_params[name] = arr.copy()

    @property
    def param_names(self):
        return self._module._param_names

    @property
    def aux_names(self):
        return self._module._aux_names

    @property
    def param_arrays(self):
        exec_ = self._module._exec
        return [[exec_.arg_dict[n]] for n in self.param_names]

    @property
    def grad_arrays(self):
        # positional 1:1 with param_arrays (None placeholder where a param
        # has no grad — reference updaters skip None in place)
        exec_ = self._module._exec
        return [[exec_.grad_dict.get(n)] for n in self.param_names]

    def load_data_batch(self, data_batch):
        self._data_batch = data_batch

    def forward(self, is_train=False):
        self._module.forward(self._data_batch, is_train=is_train)

    def backward(self):
        self._module.backward()

    def init_optimizer(self, **kwargs):
        """Attach an optimizer so :meth:`update` works (Module pass-through;
        the reference updates through an external updater instead)."""
        self._module.init_optimizer(**kwargs)

    def update(self):
        self._module.update()

    def update_metric(self, metric, labels, pre_sliced=False):
        if pre_sliced:
            labels = [l for per_dev in labels for l in per_dev]
        self._module.update_metric(metric, labels)


# the reference's executor_manager module also exposes the group class
from .module.executor_group import DataParallelExecutorGroup  # noqa: E402
