/*!
 * mxtpu C ABI — native runtime for the TPU-native framework.
 *
 * TPU-first equivalents of the reference's native core (see SURVEY.md §2.1):
 *  - dependency engine   (reference: include/mxnet/engine.h:75-250,
 *                         src/engine/threaded_engine.h) — host-side async
 *    scheduler ordering IO / staging / host-mutation work.  On TPU the
 *    *device* async scheduling is PJRT/XLA's job; this engine owns what PJRT
 *    does not: the host side of the pipeline.
 *  - pooled storage      (reference: include/mxnet/storage.h:17-75,
 *                         src/storage/pooled_storage_manager.h) — aligned
 *    host buffers for staging batches into device memory.
 *  - profiler            (reference: src/engine/profiler.h:20-141) —
 *    chrome://tracing JSON of engine op execution.
 *  - RecordIO            (reference: dmlc-core recordio + src/io) — framed
 *    record container + threaded prefetching loader (the dmlc::ThreadedIter
 *    + InputSplit role).
 *
 * Design: flat C ABI (the reference exposes 119 MXNET_DLL functions from
 * include/mxnet/c_api.h for all frontends); here ctypes is the binding layer.
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define MXTPU_DLL __attribute__((visibility("default")))

/* ---------------- engine ---------------- */

typedef void *MXTPUVarHandle;
/* Async fn executed on a worker thread; param is an opaque cookie. */
typedef void (*MXTPUFn)(void *param);

/* FnProperty: selects the worker pool (reference FnProperty classes,
 * threaded_engine_perdevice.cc:55-105). */
#define MXTPU_PROP_NORMAL 0
#define MXTPU_PROP_IO 1
#define MXTPU_PROP_COPY 2

MXTPU_DLL MXTPUVarHandle mxtpu_var_new(void);
/* Async-delete: the var dies after all previously pushed ops on it finish. */
MXTPU_DLL void mxtpu_var_delete(MXTPUVarHandle var);

/* Push fn with read deps const_vars and write deps mutable_vars.  deleter
 * (may be NULL) runs after fn completes — used by bindings to drop the
 * cookie.  Higher priority runs first within a pool. */
MXTPU_DLL void mxtpu_push(MXTPUFn fn, void *param, MXTPUFn deleter,
                          const MXTPUVarHandle *const_vars, int n_const,
                          const MXTPUVarHandle *mutable_vars, int n_mutable,
                          int priority, int prop, const char *opr_name);

MXTPU_DLL void mxtpu_wait_for_var(MXTPUVarHandle var);
MXTPU_DLL void mxtpu_wait_all(void);
/* 0 = threaded, 1 = naive(synchronous).  Selected by MXTPU_ENGINE_TYPE. */
MXTPU_DLL int mxtpu_engine_type(void);
MXTPU_DLL int mxtpu_engine_num_workers(void);
/* #ops pushed - #ops completed (diagnostics). */
MXTPU_DLL long mxtpu_engine_pending(void);

/* ---------------- storage ---------------- */

MXTPU_DLL void *mxtpu_storage_alloc(size_t size);
MXTPU_DLL void mxtpu_storage_free(void *ptr, size_t size);     /* to pool  */
MXTPU_DLL void mxtpu_storage_direct_free(void *ptr, size_t size); /* bypass */
MXTPU_DLL void mxtpu_storage_release_all(void);
MXTPU_DLL size_t mxtpu_storage_pooled_bytes(void);
MXTPU_DLL size_t mxtpu_storage_used_bytes(void);

/* ---------------- profiler ---------------- */

MXTPU_DLL void mxtpu_profiler_set_state(int running);
MXTPU_DLL int mxtpu_profiler_state(void);
/* Dump accumulated events as chrome://tracing JSON; returns #events. */
MXTPU_DLL int mxtpu_profiler_dump(const char *path);
MXTPU_DLL void mxtpu_profiler_clear(void);
/* Record an externally timed event (frontend scopes), usec timestamps. */
MXTPU_DLL void mxtpu_profiler_add_event(const char *name, const char *cat,
                                        int64_t start_us, int64_t end_us,
                                        int tid);

/* ---------------- recordio ---------------- */

MXTPU_DLL void *mxtpu_recordio_writer_open(const char *path);
MXTPU_DLL int mxtpu_recordio_writer_write(void *h, const char *buf,
                                          size_t len);
MXTPU_DLL long mxtpu_recordio_writer_tell(void *h);
MXTPU_DLL void mxtpu_recordio_writer_close(void *h);

MXTPU_DLL void *mxtpu_recordio_reader_open(const char *path);
/* 1 = record produced (malloc'd *out, caller frees via mxtpu_buf_free),
 * 0 = eof, -1 = error. */
MXTPU_DLL int mxtpu_recordio_reader_next(void *h, char **out, size_t *len);
MXTPU_DLL long mxtpu_recordio_reader_tell(void *h);
MXTPU_DLL void mxtpu_recordio_reader_close(void *h);

/* Threaded prefetching loader: background thread reads + shards + (chunk)
 * shuffles records into a bounded queue (the dmlc::ThreadedIter +
 * InputSplit role; record i belongs to this part iff i % num_parts ==
 * part_index). */
MXTPU_DLL void *mxtpu_loader_create(const char *path, int part_index,
                                    int num_parts, int shuffle,
                                    unsigned seed, int queue_size,
                                    int shuffle_chunk);
MXTPU_DLL int mxtpu_loader_next(void *h, char **out, size_t *len);
/* Pop up to max_n records in one call: outs/lens are caller arrays of
 * size max_n.  Returns the number of records produced (0 = eof, -1 =
 * error); buffers are malloc'd, caller frees each via mxtpu_buf_free. */
MXTPU_DLL int mxtpu_loader_next_batch(void *h, int max_n, char **outs,
                                      size_t *lens);
MXTPU_DLL void mxtpu_loader_reset(void *h);
MXTPU_DLL void mxtpu_loader_free(void *h);

MXTPU_DLL void mxtpu_buf_free(char *p);

/* Parallel JPEG decode + augment pipeline over the threaded loader
 * (reference iter_image_recordio_2.cc:104-112 OMP decode): n_workers
 * threads decode (libjpeg, DCT-scaled), bilinear-resize (shorter edge =
 * resize_shorter, 0 = only as needed to crop), crop out_h x out_w
 * (random iff rand_crop, else center), mirror with p=0.5 iff
 * rand_mirror.  Samples are uint8 HWC RGB.  Non-JPEG/corrupt records are
 * skipped and counted. */
MXTPU_DLL void *mxtpu_decode_loader_create(const char *path, int part_index,
                                           int num_parts, int shuffle,
                                           unsigned seed, int queue_size,
                                           int shuffle_chunk, int n_workers,
                                           int out_h, int out_w,
                                           int resize_shorter, int rand_crop,
                                           int rand_mirror);
/* Fill data (max_n*out_h*out_w*3 bytes) + labels (max_n floats); returns
 * #samples, 0 = epoch end. */
MXTPU_DLL int mxtpu_decode_loader_next_batch(void *h, int max_n,
                                             unsigned char *data,
                                             float *labels);
MXTPU_DLL long mxtpu_decode_loader_skipped(void *h);
MXTPU_DLL void mxtpu_decode_loader_reset(void *h);
MXTPU_DLL void mxtpu_decode_loader_free(void *h);

/* ---------------- NDArray (host, C ABI) ----------------
 * Minimal NDArray subset for C/C++ frontends (reference c_api.h
 * MXNDArrayCreate/Free + data access; float32, host-resident — staging
 * buffers come from the pooled storage manager).  Device arrays are the
 * Python/PJRT layer's job; this is the deployment-side data container the
 * predict API consumes. */

typedef void *MXTPUNDArrayHandle;

/* Dtype codes: the reference's mshadow TypeFlag order
 * (include/mxnet/c_api.h dtype int + mshadow/base.h kFloat32..kInt64),
 * with bfloat16 appended — the TPU-native training dtype the 2017
 * reference predates. */
#define MXTPU_DTYPE_FLOAT32 0
#define MXTPU_DTYPE_FLOAT64 1
#define MXTPU_DTYPE_FLOAT16 2
#define MXTPU_DTYPE_UINT8 3
#define MXTPU_DTYPE_INT32 4
#define MXTPU_DTYPE_INT8 5
#define MXTPU_DTYPE_INT64 6
#define MXTPU_DTYPE_BFLOAT16 7

MXTPU_DLL MXTPUNDArrayHandle mxtpu_ndarray_create(const int64_t *shape,
                                                  int ndim);
MXTPU_DLL MXTPUNDArrayHandle mxtpu_ndarray_create_dtype(const int64_t *shape,
                                                        int ndim, int dtype);
MXTPU_DLL int mxtpu_ndarray_dtype(MXTPUNDArrayHandle h);
/* float32 arrays only (NULL + error otherwise); use mxtpu_ndarray_bytes
 * for the dtype-generic payload. */
MXTPU_DLL float *mxtpu_ndarray_data(MXTPUNDArrayHandle h);
MXTPU_DLL void *mxtpu_ndarray_bytes(MXTPUNDArrayHandle h);
MXTPU_DLL size_t mxtpu_ndarray_nbytes(MXTPUNDArrayHandle h);
MXTPU_DLL int mxtpu_ndarray_ndim(MXTPUNDArrayHandle h);
MXTPU_DLL const int64_t *mxtpu_ndarray_shape(MXTPUNDArrayHandle h);
MXTPU_DLL size_t mxtpu_ndarray_size(MXTPUNDArrayHandle h);
MXTPU_DLL int mxtpu_ndarray_copy(MXTPUNDArrayHandle dst,
                                 MXTPUNDArrayHandle src);   /* 0 ok */
MXTPU_DLL void mxtpu_ndarray_free(MXTPUNDArrayHandle h);

/* ---------------- predict ----------------
 * Deployment C API over a `.mxtpu` exported artifact (reference
 * include/mxnet/c_predict_api.h MXPredCreate/SetInput/Forward/
 * GetOutputShape/GetOutput/Free).  Backed by the StableHLO artifact
 * (deploy.py export_model) executed through an embedded CPython runtime —
 * the TPU-native analogue of the reference's amalgamated predict-only
 * build.  Link against libmxtpu_predict.so.  All errors return -1/NULL;
 * mxtpu_pred_last_error() gives the message (thread-local). */

typedef void *MXTPUPredHandle;

MXTPU_DLL MXTPUPredHandle mxtpu_pred_create(const char *artifact_path);
MXTPU_DLL int mxtpu_pred_num_inputs(MXTPUPredHandle h);
MXTPU_DLL const char *mxtpu_pred_input_name(MXTPUPredHandle h, int idx);
MXTPU_DLL int mxtpu_pred_set_input(MXTPUPredHandle h, const char *name,
                                   MXTPUNDArrayHandle data);
MXTPU_DLL int mxtpu_pred_forward(MXTPUPredHandle h);
MXTPU_DLL int mxtpu_pred_num_outputs(MXTPUPredHandle h);
/* Output i's array — owned by the handle, valid until the next forward
 * or free; copy out via mxtpu_ndarray_copy if needed. */
MXTPU_DLL MXTPUNDArrayHandle mxtpu_pred_output(MXTPUPredHandle h, int idx);
MXTPU_DLL void mxtpu_pred_free(MXTPUPredHandle h);
MXTPU_DLL const char *mxtpu_pred_last_error(void);

/* ---------------- full C API: Symbol / Executor / KVStore / DataIter
 *
 * Parity: reference include/mxnet/c_api.h — MXSymbolCreateFromJSON (:645),
 * MXExecutorBindEX (:1066), MXKVStoreCreate (:1207), MXDataIterCreateIter
 * (:1292).  Every frontend binds this flat ABI (the reference's core
 * architectural contract); the implementation reuses the embedded-CPython
 * runtime built for predict, so symbol composition / executor binding /
 * kvstore semantics are exactly the TPU-native core's.
 *
 * Handles are opaque int64 ids (0 = error).  Free with mxtpu_handle_free.
 * Functions returning char* give malloc'd strings (free via
 * mxtpu_buf_free); functions returning MXTPUNDArrayHandle give OWNED host
 * arrays (free via mxtpu_ndarray_free).  On error: 0/-1/NULL +
 * mxtpu_capi_last_error() (thread-local).  Link libmxtpu_predict.so. */

typedef int64_t MXTPUHandle;

MXTPU_DLL int mxtpu_handle_free(MXTPUHandle h);
MXTPU_DLL const char *mxtpu_capi_last_error(void);

/* Symbol.  kwargs_json: operator parameters as a JSON object, e.g.
 * "{\"num_hidden\": 128}".  Compose wires named inputs into an atomic
 * symbol in place (reference MXSymbolCreateAtomicSymbol + MXSymbolCompose
 * two-step). */
MXTPU_DLL MXTPUHandle mxtpu_sym_create_variable(const char *name);
MXTPU_DLL MXTPUHandle mxtpu_sym_create_atomic(const char *op_name,
                                              const char *kwargs_json);
MXTPU_DLL int mxtpu_sym_compose(MXTPUHandle sym, const char *name,
                                int n_args, const char **arg_names,
                                const MXTPUHandle *args);
MXTPU_DLL MXTPUHandle mxtpu_sym_from_json(const char *json);
MXTPU_DLL char *mxtpu_sym_to_json(MXTPUHandle sym);
/* which: "arguments" | "outputs" | "auxiliary_states"; returns a JSON
 * array of names. */
MXTPU_DLL char *mxtpu_sym_list(MXTPUHandle sym, const char *which);
/* shapes_json: {"data": [64,1,28,28], ...} -> JSON
 * {"arg": [...], "out": [...], "aux": [...]} (reference
 * MXSymbolInferShape). */
MXTPU_DLL char *mxtpu_sym_infer_shape(MXTPUHandle sym,
                                      const char *shapes_json);

/* Executor (reference MXExecutorSimpleBind/Forward/Backward tier).
 * kind: "arg" | "grad" | "aux". */
MXTPU_DLL MXTPUHandle mxtpu_executor_simple_bind(MXTPUHandle sym,
                                                 const char *shapes_json,
                                                 const char *grad_req);
MXTPU_DLL int mxtpu_executor_forward(MXTPUHandle ex, int is_train);
MXTPU_DLL int mxtpu_executor_backward(MXTPUHandle ex);
MXTPU_DLL int mxtpu_executor_num_outputs(MXTPUHandle ex);
MXTPU_DLL MXTPUNDArrayHandle mxtpu_executor_output(MXTPUHandle ex, int idx);
MXTPU_DLL MXTPUNDArrayHandle mxtpu_executor_get_array(MXTPUHandle ex,
                                                      const char *kind,
                                                      const char *name);
MXTPU_DLL int mxtpu_executor_set_array(MXTPUHandle ex, const char *kind,
                                       const char *name,
                                       MXTPUNDArrayHandle val);
/* Python-compatible two-file checkpoint (reference save_checkpoint:
 * prefix-symbol.json + prefix-%04d.params, arg:/aux: prefixed) from a
 * bound executor's state — a C/C++-trained model loads straight into
 * the Python frontend, and vice versa.  Inputs are excluded from the
 * params file by NAME CONVENTION: arguments called "data" or ending in
 * "_label" (the reference's data/label naming) are treated as inputs;
 * use those names for your input variables or prune the file yourself. */
MXTPU_DLL int mxtpu_executor_save_checkpoint(MXTPUHandle ex, MXTPUHandle sym,
                                             const char *prefix, int epoch);
MXTPU_DLL int mxtpu_executor_load_params(MXTPUHandle ex, const char *path);

/* KVStore (reference MXKVStoreCreate/Init/Push/Pull/SetOptimizer tier;
 * server-side-optimizer semantics included). */
MXTPU_DLL MXTPUHandle mxtpu_kvstore_create(const char *type);
MXTPU_DLL int mxtpu_kvstore_init(MXTPUHandle kv, const char *key,
                                 MXTPUNDArrayHandle val);
MXTPU_DLL int mxtpu_kvstore_push(MXTPUHandle kv, const char *key,
                                 MXTPUNDArrayHandle grad);
MXTPU_DLL MXTPUNDArrayHandle mxtpu_kvstore_pull(MXTPUHandle kv,
                                                const char *key,
                                                const int64_t *shape,
                                                int ndim);
MXTPU_DLL int mxtpu_kvstore_set_optimizer(MXTPUHandle kv, const char *name,
                                          const char *kwargs_json);
MXTPU_DLL int mxtpu_kvstore_rank(MXTPUHandle kv);
MXTPU_DLL int mxtpu_kvstore_num_workers(MXTPUHandle kv);

/* DataIter (reference MXDataIterCreateIter tier): registry name +
 * JSON kwargs, e.g. mxtpu_dataiter_create("CSVIter",
 * "{\"data_csv\": \"x.csv\", \"data_shape\": [784], \"batch_size\": 32}").
 * next: 1 = batch ready, 0 = epoch end, -1 = error. */
MXTPU_DLL MXTPUHandle mxtpu_dataiter_create(const char *type,
                                            const char *kwargs_json);
MXTPU_DLL int mxtpu_dataiter_next(MXTPUHandle it);
MXTPU_DLL int mxtpu_dataiter_reset(MXTPUHandle it);
MXTPU_DLL MXTPUNDArrayHandle mxtpu_dataiter_data(MXTPUHandle it);
MXTPU_DLL MXTPUNDArrayHandle mxtpu_dataiter_label(MXTPUHandle it);

/* ---------------- imperative NDArray tier ----------------
 * Device-resident arrays + imperative op invocation (parity: reference
 * MXImperativeInvoke, src/c_api/c_api_ndarray.cc:322 — the entire
 * mx.nd.* surface callable from C).  Device arrays are MXTPUHandle ids
 * living in the embedded TPU-native core (any dtype, incl. bfloat16);
 * mxtpu_nd_to_device / mxtpu_nd_from_device cross the host<->device
 * boundary dtype-losslessly. */

MXTPU_DLL MXTPUHandle mxtpu_nd_to_device(MXTPUNDArrayHandle host);
MXTPU_DLL MXTPUNDArrayHandle mxtpu_nd_from_device(MXTPUHandle dev);
/* Invoke a registry op on device arrays: kwargs_json as in
 * mxtpu_sym_create_atomic.  Writes up to max_outputs handles; returns
 * the output count, or -1 on error. */
MXTPU_DLL int mxtpu_imperative_invoke(const char *op_name,
                                      const char *kwargs_json, int n_inputs,
                                      const MXTPUHandle *inputs,
                                      int max_outputs, MXTPUHandle *outputs);

/* ---------------- autograd ----------------
 * Imperative autograd over the device-array tier (parity: reference
 * MXAutogradSetIsTraining / MXAutogradMarkVariables /
 * MXAutogradComputeGradient, include/mxnet/c_api.h + contrib
 * autograd.py:14-188).  While recording is on, every
 * mxtpu_imperative_invoke is taped; backward replays the tape under
 * jax.vjp and fills the gradient arrays returned by mark_variables. */

MXTPU_DLL int mxtpu_autograd_set_recording(int on);
/* For each vars[i], creates a zero gradient device array grads[i]
 * (caller frees each via mxtpu_handle_free). */
MXTPU_DLL int mxtpu_autograd_mark_variables(int n, const MXTPUHandle *vars,
                                            MXTPUHandle *grads);
MXTPU_DLL int mxtpu_autograd_backward(int n, const MXTPUHandle *outputs);

/* ---------------- misc ---------------- */
MXTPU_DLL const char *mxtpu_version(void);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_C_API_H_ */
