"""Imperative autograd (parity: reference
``python/mxnet/contrib/autograd.py:14-188`` over the ``MXAutograd*`` C API and
``src/ndarray/autograd.cc``).

The reference tapes imperative ops into an NNVM graph, then binds a throwaway
GraphExecutor to compute gradients.  Here the tape records (op, attrs, inputs)
and ``compute_gradient`` replays it as a pure function under ``jax.vjp`` —
the functional equivalent of "build Symbol from tape and run Backward".
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from .. import random as _random
from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["set_is_training", "train_section", "test_section", "mark_variables",
           "backward", "compute_gradient", "grad_and_loss", "grad"]

_STATE = {"is_training": False}
_TAPE: List = []          # list of (op, attrs, in_entries, out_entries, n_aux)
_MARKED: Dict[int, NDArray] = {}  # id(NDArray) -> grad NDArray


def set_is_training(is_train):
    """(parity: ``autograd.py:set_is_training``)"""
    prev = _STATE["is_training"]
    _STATE["is_training"] = is_train
    if is_train and not prev:
        _TAPE.clear()
    return prev


def is_training():
    return _STATE["is_training"]


class TrainingStateScope(object):
    """Scope for managing training state (parity: ``TrainingStateScope``)."""

    def __init__(self, enter_state):
        self._enter_state = enter_state
        self._prev = None

    def __enter__(self):
        self._prev = set_is_training(self._enter_state)

    def __exit__(self, ptype, value, trace):
        if self._prev != self._enter_state:
            set_is_training(self._prev)


def train_section():
    """Activate training-mode taping (parity: ``autograd.py:train_section``)."""
    return TrainingStateScope(True)


def test_section():
    """(parity: ``autograd.py:test_section``)"""
    return TrainingStateScope(False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Associate gradient buffers with variables (parity: ``mark_variables``)."""
    if isinstance(variables, NDArray):
        variables = [variables]
        gradients = [gradients]
    for var, gradvar in zip(variables, gradients):
        var._tape_entry = ("var", id(var))
        _MARKED[id(var)] = (var, gradvar)


def _record(op, attrs, inputs, outputs, n_args):
    """Called by ndarray.invoke when taping is active."""
    in_entries = []
    for x in inputs:
        if isinstance(x, NDArray):
            in_entries.append(("nd", id(x), x._data))
        else:
            in_entries.append(("const", None, x))
    out_entries = [id(o) for o in outputs]
    _TAPE.append((op, dict(attrs), in_entries, out_entries, n_args))
    for o in outputs:
        o._tape_entry = ("out", id(o))


def backward(outputs, out_grads=None, retain_graph=False):
    """Compute gradients of marked variables w.r.t. outputs (parity:
    ``autograd.py:backward``)."""
    compute_gradient(outputs, out_grads)


def compute_gradient(outputs, out_grads=None):
    """(parity: ``autograd.py:compute_gradient``)"""
    if not _MARKED:
        raise MXNetError("no variables marked; call mark_variables first")
    marked = {k: v for k, v in _MARKED.items()}
    tape = list(_TAPE)

    # assemble pure replay function over the marked variables
    var_ids = list(marked)
    var_vals = {vid: marked[vid][0]._data for vid in var_ids}

    def replay(vals):
        env = dict(vals)  # id -> array

        def lookup(entry):
            kind, key, payload = entry
            if kind == "nd" and key in env:
                return env[key]
            return payload

        rng = _random.current_key()
        for i, (op, attrs, in_entries, out_ids, n_args) in enumerate(tape):
            args = [lookup(e) for e in in_entries[:n_args]]
            auxs = [lookup(e) for e in in_entries[n_args:]]
            node_rng = jax.random.fold_in(rng, i) if op.needs_rng else None
            outs, _ = op.apply(attrs, args, auxs, is_train=True, rng=node_rng)
            for oid, o in zip(out_ids, outs):
                env[oid] = o
        return [env[id(o)] for o in outputs]

    out_vals, vjp_fn = jax.vjp(replay, var_vals)
    if out_grads is None:
        cots = [jnp.ones_like(o) for o in out_vals]
    else:
        cots = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                for g in out_grads]
    grads = vjp_fn(cots)[0]
    for vid, g in grads.items():
        var, gradvar = marked[vid]
        gradvar._set_data(g)


def grad_and_loss(func, argnum=None):
    """Return a function computing both gradient and loss (parity:
    ``autograd.py:grad_and_loss``)."""

    @functools.wraps(func)
    def wrapped(*args):
        variables = args
        if argnum is not None:
            argnum_ = argnum if isinstance(argnum, list) else [argnum]
            variables = [args[i] for i in argnum_]
        for x in variables:
            assert isinstance(x, NDArray), "type of autograd input should NDArray."
        grads = [NDArray(jnp.zeros_like(x._data), x._ctx) for x in variables]
        mark_variables(variables, grads)
        with train_section():
            outputs = func(*args)
        compute_gradient([outputs] if isinstance(outputs, NDArray) else outputs)
        return grads, outputs

    return wrapped


def grad(func, argnum=None):
    """Gradient-only version of grad_and_loss (parity: ``autograd.py:grad``)."""
    grad_with_loss_func = grad_and_loss(func, argnum)

    @functools.wraps(grad_with_loss_func)
    def wrapped(*args):
        return grad_with_loss_func(*args)[0]

    return wrapped


# reference-compat names: train()/test() scopes (the reference exposes both
# spellings; ``with autograd.train():``)
train = train_section
test = test_section
