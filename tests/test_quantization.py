"""Model-level PTQ passes (mxnet_tpu.contrib.quantization): BN fold
exactness, int8 graph rewrite vs fake-quant parity, NHWC quantized conv,
and the __dtype__ variable-hint plumbing the rewrite relies on."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import quantization as Q


def _fwd(sym, args, auxs, x, ctx=None):
    exe = sym.simple_bind(ctx or mx.cpu(), grad_req="null",
                          data=tuple(x.shape))
    for k, v in args.items():
        if k in exe.arg_dict:
            exe.arg_dict[k][:] = v
    for k, v in auxs.items():
        if k in exe.aux_dict:
            exe.aux_dict[k][:] = v
    exe.arg_dict["data"][:] = x
    return exe.forward(is_train=False)[0].asnumpy()


def _conv_bn_net(layout=None, no_bias=True):
    kw = {"layout": layout} if layout else {}
    net = mx.sym.Convolution(mx.sym.Variable("data"), kernel=(3, 3),
                             num_filter=8, pad=(1, 1), no_bias=no_bias,
                             name="conv0", **kw)
    net = mx.sym.BatchNorm(net, name="bn0", fix_gamma=False,
                           **({"axis": 3} if layout == "NHWC" else {}))
    net = mx.sym.Activation(net, act_type="relu", name="relu0")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=5,
                                name="fc1")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _params(rng, layout=None, no_bias=True):
    wshape = (8, 3, 3, 4) if layout == "NHWC" else (8, 4, 3, 3)
    args = {"conv0_weight": mx.nd.array(rng.randn(*wshape) * 0.2),
            "bn0_gamma": mx.nd.array(rng.rand(8) + 0.5),
            "bn0_beta": mx.nd.array(rng.randn(8) * 0.1),
            "fc1_weight": mx.nd.array(rng.randn(5, 8 * 36) * 0.1),
            "fc1_bias": mx.nd.array(rng.randn(5) * 0.1)}
    if not no_bias:
        args["conv0_bias"] = mx.nd.array(rng.randn(8) * 0.1)
    auxs = {"bn0_moving_mean": mx.nd.array(rng.randn(8) * 0.1),
            "bn0_moving_var": mx.nd.array(rng.rand(8) + 0.5)}
    return args, auxs


def _data(rng, layout=None):
    return (rng.randn(4, 6, 6, 4) if layout == "NHWC"
            else rng.randn(4, 4, 6, 6)).astype(np.float32)


@pytest.mark.parametrize("no_bias", [True, False])
def test_fold_bn_exact(no_bias):
    """Folded conv+bias must equal conv->BN(inference stats) to float
    rounding; gamma/beta/moving stats disappear from the params."""
    rng = np.random.RandomState(0)
    net = _conv_bn_net(no_bias=no_bias)
    args, auxs = _params(rng, no_bias=no_bias)
    x = _data(rng)
    y0 = _fwd(net, args, auxs, x)
    fsym, fargs, fauxs = Q.fold_bn(net, args, auxs)
    y1 = _fwd(fsym, fargs, fauxs, x)
    np.testing.assert_allclose(y1, y0, rtol=1e-5, atol=1e-6)
    assert "bn0_gamma" not in fargs and "bn0_moving_mean" not in fauxs
    assert "conv0_bias" in fargs
    assert "bn0" not in fsym.tojson()


def test_fold_bn_skips_shared_conv_output():
    """A conv whose output feeds the BN AND something else must not fold
    (the scale would corrupt the second consumer)."""
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, kernel=(1, 1), num_filter=4,
                              no_bias=True, name="convs")
    bn = mx.sym.BatchNorm(conv, name="bns")
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Flatten(bn + conv), num_hidden=3, name="fcs"),
        name="softmax")
    rng = np.random.RandomState(1)
    args = {"convs_weight": mx.nd.array(rng.randn(4, 2, 1, 1)),
            "bns_gamma": mx.nd.array(rng.rand(4) + 0.5),
            "bns_beta": mx.nd.array(rng.randn(4)),
            "fcs_weight": mx.nd.array(rng.randn(3, 4 * 9) * 0.1),
            "fcs_bias": mx.nd.array(rng.randn(3))}
    auxs = {"bns_moving_mean": mx.nd.array(rng.randn(4) * 0.1),
            "bns_moving_var": mx.nd.array(rng.rand(4) + 0.5)}
    fsym, fargs, fauxs = Q.fold_bn(net, args, auxs)
    assert "BatchNorm" in fsym.tojson()  # kept, not corrupted
    x = rng.randn(2, 2, 3, 3).astype(np.float32)
    np.testing.assert_allclose(_fwd(fsym, fargs, fauxs, x),
                               _fwd(net, args, auxs, x),
                               rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("layout", [None, "NHWC"])
def test_quantize_model_end_to_end(layout):
    """Full pipeline on both conv layouts: int8 predictions track fp32
    closely on in-range data (symmetric calib on the same batch)."""
    rng = np.random.RandomState(2)
    net = _conv_bn_net(layout=layout)
    args, auxs = _params(rng, layout=layout)
    x = _data(rng, layout=layout)
    y0 = _fwd(net, args, auxs, x)
    qsym, qargs, qauxs = Q.quantize_model(net, args, auxs,
                                          [{"data": x}], mx.cpu())
    y1 = _fwd(qsym, qargs, qauxs, x)
    assert qargs["conv0_weight"].asnumpy().dtype == np.int8
    assert qargs["fc1_weight"].asnumpy().dtype == np.int8
    # int8 quantization noise on softmax probabilities
    np.testing.assert_allclose(y1, y0, atol=0.02)
    assert (y1.argmax(axis=1) == y0.argmax(axis=1)).mean() == 1.0


def test_quantize_excluded_nodes_stay_float():
    rng = np.random.RandomState(3)
    net = _conv_bn_net()
    args, auxs = _params(rng)
    x = _data(rng)
    qsym, qargs, qauxs = Q.quantize_model(
        net, args, auxs, [{"data": x}], mx.cpu(),
        excluded_sym_names=["conv0"])
    assert qargs["conv0_weight"].asnumpy().dtype == np.float32
    assert qargs["fc1_weight"].asnumpy().dtype == np.int8
    j = qsym.tojson()
    assert "_contrib_quantized_conv" not in j
    assert "_contrib_quantized_fully_connected" in j


def test_dtype_hint_drives_simple_bind_allocation():
    """__dtype__ Variable hints must survive into simple_bind's array
    allocation (int8 params bind as int8 without a type_dict)."""
    v = mx.sym.Variable("w", shape=(4, 4), dtype="int8")
    out = mx.sym.Cast(v, dtype="float32")
    exe = out.simple_bind(mx.cpu(), grad_req="null")
    assert exe.arg_dict["w"].asnumpy().dtype == np.int8


def test_quantize_tied_weight_with_excluded_consumer_raises():
    """A weight shared between a quantized node and an excluded one
    would be silently rewritten to int8 codes under the float consumer —
    must refuse loudly."""
    from mxnet_tpu.base import MXNetError

    rng = np.random.RandomState(5)
    d = mx.sym.Variable("data")
    w = mx.sym.Variable("shared_w")
    f1 = mx.sym.FullyConnected(d, weight=w, num_hidden=6, no_bias=True,
                               name="fc1")
    f2 = mx.sym.FullyConnected(d, weight=w, num_hidden=6, no_bias=True,
                               name="fc2")
    net = mx.sym.SoftmaxOutput(f1 + f2, name="softmax")
    args = {"shared_w": mx.nd.array(rng.randn(6, 4))}
    with pytest.raises(MXNetError, match="shared"):
        Q.quantize_symbol(net, args, {"fc1": 1.0},
                          excluded_sym_names=["fc2"])
    # both quantized: legal; the tied weight quantizes once with one range
    qsym, qargs = Q.quantize_symbol(net, args, {"fc1": 1.0, "fc2": 1.0})
    assert qargs["shared_w"].asnumpy().dtype == np.int8
    assert np.asarray(qargs["fc1_weight_max"].asnumpy()) \
        == np.asarray(qargs["fc2_weight_max"].asnumpy())


def test_quantize_shared_input_single_quantize_node():
    """Two convs reading the same tensor (the ResNet downsample-block
    shape) share ONE _contrib_quantize node — not one per consumer."""
    rng = np.random.RandomState(6)
    d = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(d, kernel=(1, 1), num_filter=4, no_bias=True,
                            name="ca")
    c2 = mx.sym.Convolution(d, kernel=(3, 3), num_filter=4, pad=(1, 1),
                            no_bias=True, name="cb")
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Flatten(c1 + c2), num_hidden=3, name="fcq"),
        name="softmax")
    args = {"ca_weight": mx.nd.array(rng.randn(4, 2, 1, 1)),
            "cb_weight": mx.nd.array(rng.randn(4, 2, 3, 3) * 0.2),
            "fcq_weight": mx.nd.array(rng.randn(3, 4 * 25) * 0.1),
            "fcq_bias": mx.nd.array(rng.randn(3))}
    x = rng.randn(2, 2, 5, 5).astype(np.float32)
    qsym, qargs, qauxs = Q.quantize_model(net, args, {}, [{"data": x}],
                                          mx.cpu())
    j = qsym.tojson()
    # ca+cb share one quantize of `data`; the FC has its own
    assert j.count('"_contrib_quantize"') == 2
    y = _fwd(qsym, qargs, qauxs, x)
    y0 = _fwd(net, args, {}, x)
    assert (y.argmax(axis=1) == y0.argmax(axis=1)).all()


def test_quantize_bf16_outputs():
    """out_dtype='bfloat16' (the chip-winning configuration —
    docs/PERF.md int8-at-model-level): rescaled outputs and biases carry
    bf16, predictions stay within bf16+int8 noise of fp32."""
    rng = np.random.RandomState(7)
    net = _conv_bn_net()
    args, auxs = _params(rng)
    x = _data(rng)
    y0 = _fwd(net, args, auxs, x)
    qsym, qargs, qauxs = Q.quantize_model(net, args, auxs, [{"data": x}],
                                          mx.cpu(), out_dtype="bfloat16")
    y1 = _fwd(qsym, qargs, qauxs, x).astype(np.float32)
    np.testing.assert_allclose(y1, y0, atol=0.03)
    assert (y1.argmax(axis=1) == y0.argmax(axis=1)).mean() == 1.0
    assert str(qargs["conv0_bias"].asnumpy().dtype) == "bfloat16"


# ---------------------------------------------------------------------
# QAT: fake-quant op semantics + insert/finetune/export pipeline
# ---------------------------------------------------------------------


def test_fake_quant_op_ste_and_ema():
    """Clipped STE: gradient 1 inside [-amax, amax], 0 outside; EMA
    observer seeds from the first batch then tracks with momentum; an
    empty observer passes eval-mode data through unchanged."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.registry import get_op

    op = get_op("_contrib_fake_quant")
    attrs = {"ema_momentum": 0.9, "num_bits": 8}
    amax = jnp.array([1.0], jnp.float32)

    def f(xx):
        return op.apply(attrs, [xx], [amax], is_train=False)[0][0].sum()

    g = jax.grad(f)(jnp.array([0.5, -2.0, 3.0, 0.01], jnp.float32))
    np.testing.assert_allclose(np.asarray(g), [1.0, 0.0, 0.0, 1.0])

    # forward snaps to the 127-level grid
    y = op.apply(attrs, [jnp.array([0.5004, 2.0])], [amax],
                 is_train=False)[0][0]
    np.testing.assert_allclose(
        np.asarray(y), [np.round(0.5004 * 127) / 127, 1.0], rtol=1e-6)

    # observer: first batch seeds, then EMA
    _, aux = op.apply(attrs, [jnp.array([2.0, -4.0])],
                      [jnp.array([0.0])], is_train=True)
    assert float(aux[0][0]) == 4.0
    _, aux = op.apply(attrs, [jnp.array([2.0, -4.0])],
                      [jnp.array([8.0])], is_train=True)
    np.testing.assert_allclose(float(aux[0][0]), 0.9 * 8 + 0.1 * 4)

    # empty observer (amax=0) in eval: identity
    y, aux = op.apply(attrs, [jnp.array([0.123, -7.0])],
                      [jnp.array([0.0])], is_train=False)
    np.testing.assert_allclose(np.asarray(y[0]), [0.123, -7.0])


def _blobs(rng, n=400, d=16, k=4):
    centers = rng.randn(k, d) * 3.0
    labels = rng.randint(0, k, n)
    data = (centers[labels] + rng.randn(n, d)).astype(np.float32)
    return data, labels.astype(np.float32)


def _mlp(k=4):
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=32,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    return mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(net, num_hidden=k, name="fc2"),
        name="softmax")


def test_qat_pipeline_mlp():
    """Train fp32 -> insert fake-quant -> finetune (observers fill via
    the aux-update path) -> export: the int8 graph's outputs match the
    QAT graph's eval-mode forward (same grids by construction) and
    accuracy holds."""
    rng = np.random.RandomState(0)
    data, labels = _blobs(rng)
    it = mx.io.NDArrayIter(data, labels, batch_size=40, shuffle=True)
    net = _mlp()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=8, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
            initializer=mx.initializer.Xavier())
    args, _ = mod.get_params()

    qat = Q.quantize_aware_symbol(net)
    # one observer per distinct data tensor, dynamic fq per weight
    assert sorted(qat.list_auxiliary_states()) == [
        "activation0_fq_amax", "data_fq_amax"]
    m2 = mx.mod.Module(qat, context=mx.cpu())
    it.reset()
    m2.fit(it, num_epoch=4, optimizer="sgd",
           optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
           arg_params=args, aux_params={}, allow_missing=True,
           initializer=mx.initializer.Xavier())
    qargs, qauxs = m2.get_params()
    assert all(float(v.asnumpy().max()) > 0 for v in qauxs.values())
    acc_qat = m2.score(mx.io.NDArrayIter(data, labels, batch_size=40),
                       "acc")[0][1]
    assert acc_qat > 0.95, acc_qat

    qsym, qa, qx = Q.quantize_model_qat(qat, qargs, qauxs)
    ops = [n["op"] for n in __import__("json").loads(qsym.tojson())["nodes"]]
    assert "_contrib_quantized_fully_connected" in ops
    assert "_contrib_fake_quant" not in ops
    m3 = mx.mod.Module(qsym, context=mx.cpu())
    m3.bind(data_shapes=[("data", (40, 16))],
            label_shapes=[("softmax_label", (40,))], for_training=False)
    m3.set_params(qa, qx)
    acc_int8 = m3.score(mx.io.NDArrayIter(data, labels, batch_size=40),
                        "acc")[0][1]
    assert acc_int8 > 0.95, acc_int8

    # eval-mode QAT forward == int8 graph forward (shared grids)
    m2p = mx.mod.Module(qat, context=mx.cpu())
    m2p.bind(data_shapes=[("data", (40, 16))],
             label_shapes=[("softmax_label", (40,))], for_training=False)
    m2p.set_params(qargs, qauxs)
    b = mx.io.NDArrayIter(data[:40], labels[:40], batch_size=40)
    o_sim = m2p.predict(b).asnumpy()
    b.reset()
    o_int8 = m3.predict(b).asnumpy()
    np.testing.assert_allclose(o_sim, o_int8, rtol=1e-5, atol=1e-6)


def test_qat_conv_after_fold():
    """The documented convnet flow: fold_bn first, then QAT-finetune the
    folded graph (convs carry the folded bias), then export — the conv
    becomes a quantized conv and the graph still runs."""
    rng = np.random.RandomState(3)
    net = _conv_bn_net()
    args, auxs = _params(rng)
    fsym, fargs, fauxs = Q.fold_bn(net, args, auxs)
    qat = Q.quantize_aware_symbol(fsym)
    x = _data(rng)
    labels = rng.randint(0, 5, 4).astype(np.float32)
    m = mx.mod.Module(qat, context=mx.cpu())
    it = mx.io.NDArrayIter(x, labels, batch_size=4)
    m.fit(it, num_epoch=2, optimizer="sgd",
          optimizer_params={"learning_rate": 0.01},
          arg_params=dict(fargs), aux_params={}, allow_missing=True,
          initializer=mx.initializer.Xavier())
    qargs, qauxs = m.get_params()
    qsym, qa, qx = Q.quantize_model_qat(qat, qargs, qauxs)
    ops = [n["op"] for n in __import__("json").loads(qsym.tojson())["nodes"]]
    assert "_contrib_quantized_conv" in ops
    out = _fwd(qsym, {k: v.asnumpy() for k, v in qa.items()},
               {k: v.asnumpy() for k, v in qx.items()}, x)
    assert out.shape == (4, 5)
    assert np.isfinite(out).all()


def test_qat_shared_input_one_observer():
    """Two FCs reading the same tensor share ONE observer node (the
    shared-``_contrib_quantize`` rule's training twin)."""
    import json as _json

    d = mx.sym.Variable("data")
    a = mx.sym.FullyConnected(d, num_hidden=4, name="fca")
    b = mx.sym.FullyConnected(d, num_hidden=4, name="fcb")
    qat = Q.quantize_aware_symbol(mx.sym.Group([a, b]))
    nodes = _json.loads(qat.tojson())["nodes"]
    fq_obs = [n for n in nodes if n["op"] == "_contrib_fake_quant"]
    assert len(fq_obs) == 1, [n["name"] for n in fq_obs]


def test_qat_export_empty_observer_raises():
    """Exporting before any training batch must fail loudly, naming the
    empty observer."""
    net = _mlp()
    qat = Q.quantize_aware_symbol(net)
    rng = np.random.RandomState(0)
    args = {"fc1_weight": mx.nd.array(rng.randn(32, 16) * 0.1),
            "fc1_bias": mx.nd.zeros((32,)),
            "fc2_weight": mx.nd.array(rng.randn(4, 32) * 0.1),
            "fc2_bias": mx.nd.zeros((4,))}
    auxs = {k: mx.nd.zeros((1,)) for k in qat.list_auxiliary_states()}
    with pytest.raises(mx.base.MXNetError, match="empty"):
        Q.quantize_model_qat(qat, args, auxs)


def test_qat_dual_role_tensor_gets_both_fq_types():
    """A tensor consumed as one node's DATA and another's WEIGHT needs
    both fake-quant flavors: an EMA observer on the data edge and a
    dynamic fq on the weight edge — the cache must key on role, not just
    on the source tensor."""
    import json as _json

    d = mx.sym.Variable("data")
    w = mx.sym.Variable("shared")
    fca = mx.sym.FullyConnected(d, weight=w, num_hidden=16, no_bias=True,
                                name="fca")
    fcb = mx.sym.FullyConnected(w, num_hidden=4, no_bias=True, name="fcb")
    qat = Q.quantize_aware_symbol(mx.sym.Group([fca, fcb]))
    nodes = _json.loads(qat.tojson())["nodes"]
    by_name = {n["name"]: n for n in nodes}
    names = [n["name"] for n in nodes]

    def _input_op(consumer, idx):
        return nodes[by_name[consumer]["inputs"][idx][0]]["op"]

    # fcb reads `shared` as data -> EMA observer (with amax aux);
    # fca reads `shared` as weight -> dynamic fq; both must exist
    assert _input_op("fcb", 0) == "_contrib_fake_quant"
    assert _input_op("fca", 1) == "_contrib_fake_quant_dynamic"
    assert "shared_fq" in names and "shared_fqw" in names
    assert "shared_fq_amax" in qat.list_auxiliary_states()


def test_qat_export_num_bits_mismatch_raises():
    """quantize_symbol deploys a hard int8/127 grid; a graph finetuned at
    another width must refuse to export rather than silently change the
    quantization the training simulated."""
    net = _mlp()
    qat = Q.quantize_aware_symbol(net, num_bits=4)
    rng = np.random.RandomState(0)
    args = {"fc1_weight": mx.nd.array(rng.randn(32, 16) * 0.1),
            "fc1_bias": mx.nd.zeros((32,)),
            "fc2_weight": mx.nd.array(rng.randn(4, 32) * 0.1),
            "fc2_bias": mx.nd.zeros((4,))}
    auxs = {k: mx.nd.array([1.0]) for k in qat.list_auxiliary_states()}
    with pytest.raises(mx.base.MXNetError, match="num_bits=4"):
        Q.quantize_model_qat(qat, args, auxs)


def test_qat_export_missing_observer_warns(caplog):
    """Excluding a node at insertion but not at export leaves it with no
    observer: the export must warn (the node silently stays float)
    instead of skipping it without a trace."""
    import json as _json
    import logging

    net = _mlp()
    qat = Q.quantize_aware_symbol(net, excluded_sym_names=("fc2",))
    rng = np.random.RandomState(0)
    args = {"fc1_weight": mx.nd.array(rng.randn(32, 16) * 0.1),
            "fc1_bias": mx.nd.zeros((32,)),
            "fc2_weight": mx.nd.array(rng.randn(4, 32) * 0.1),
            "fc2_bias": mx.nd.zeros((4,))}
    auxs = {k: mx.nd.array([1.0]) for k in qat.list_auxiliary_states()}
    with caplog.at_level(logging.WARNING):
        qsym, _qa, _qx = Q.quantize_model_qat(qat, args, auxs)
    assert any("fc2" in r.message and "observer" in r.message
               for r in caplog.records), caplog.records
    ops = {n["name"]: n["op"] for n in _json.loads(qsym.tojson())["nodes"]}
    assert ops["fc2"] == "FullyConnected"  # stayed float
    assert ops["fc1"].startswith("_contrib_quantized")
