"""RecordIO — record-packed dataset container (parity: reference
``python/mxnet/recordio.py`` + dmlc-core recordio).

Binary format is kept compatible with the reference: records framed with the
dmlc magic ``0xced7230a`` + length word (upper 3 bits = continuation flag),
payloads padded to 4 bytes; ``IRHeader`` packs (flag, label, id, id2) with
``struct '<IfQQ'`` exactly as ``recordio.py:19-168``.  Sequential read and
all writes go through the native C++ backend (``native/src/recordio.cc``)
when built — the dmlc-core recordio role; indexed random access stays in
Python.  Set ``MXTPU_NO_NATIVE=1`` to force pure Python.
"""

from __future__ import annotations

import ctypes
import numbers
import os
import struct
from collections import namedtuple

import numpy as np

from . import _native
from . import base
from . import chaos as _chaos
from .observability import metrics as _metrics

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_M_CORRUPT = _metrics.counter(
    "stream_records_corrupt_total",
    "RecordIO records skipped by skip_corrupt=True readers, by stream",
    ["stream"])

_FORCE_PYTHON = False  # test hook: force the pure-Python backend

_MAGIC = 0xCED7230A
_LREC_KIND_BITS = 29


def _encode_lrec(cflag, length):
    return (cflag << _LREC_KIND_BITS) | length


def _decode_lrec(rec):
    return (rec >> _LREC_KIND_BITS) & 7, rec & ((1 << _LREC_KIND_BITS) - 1)


class MXRecordIO(object):
    """Sequential RecordIO reader/writer (parity: ``recordio.py:MXRecordIO``).

    A truncated or garbled record surfaces as
    :class:`~mxnet_tpu.base.CorruptMessageError` — never ``struct.error``
    and never silent garbage.  ``skip_corrupt=True`` opts a reader into
    degraded streaming mode: a corrupt record is counted
    (``stream_records_corrupt_total`` and :attr:`skipped_corrupt`), the
    stream resyncs by scanning for the next 4-byte-aligned magic word,
    and reading continues; corruption at EOF counts and ends the stream
    cleanly (``None``).  Resync needs ``seek``, so a skipping reader
    always uses the Python file handle, never the sequential-only native
    reader.
    """

    def __init__(self, uri, flag, skip_corrupt=False):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.skip_corrupt = bool(skip_corrupt)
        self.skipped_corrupt = 0
        self.open()

    def open(self):
        self._nh = None
        self._nlib = None if _FORCE_PYTHON else _native.lib()
        if self.flag == "w":
            if self._nlib is not None:
                self._nh = self._nlib.mxtpu_recordio_writer_open(
                    self.uri.encode())
            self.handle = None if self._nh else open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            # native reader is sequential-only; subclasses needing seek()
            # (MXIndexedRecordIO, skip_corrupt resync) stay on the Python
            # file handle
            if (self._nlib is not None and type(self) is MXRecordIO
                    and not self.skip_corrupt):
                self._nh = self._nlib.mxtpu_recordio_reader_open(
                    self.uri.encode())
            self.handle = None if self._nh else open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)

    def close(self):
        if getattr(self, "_nh", None):
            if self.writable:
                self._nlib.mxtpu_recordio_writer_close(self._nh)
            else:
                self._nlib.mxtpu_recordio_reader_close(self._nh)
            self._nh = None
        if self.handle is not None:
            self.handle.close()
            self.handle = None

    def __del__(self):
        self.close()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        if self._nh:
            if self.writable:
                return self._nlib.mxtpu_recordio_writer_tell(self._nh)
            return self._nlib.mxtpu_recordio_reader_tell(self._nh)
        return self.handle.tell()

    def write(self, buf):
        assert self.writable
        buf = bytes(buf)  # accept bytearray/memoryview on both backends
        if len(buf) >= 1 << _LREC_KIND_BITS:
            raise ValueError("record too large for RecordIO framing "
                             "(%d >= 2^29 bytes)" % len(buf))
        if self._nh:
            if self._nlib.mxtpu_recordio_writer_write(
                    self._nh, buf, len(buf)) != 0:
                raise IOError("native recordio write failed")
            return
        self.handle.write(struct.pack("<II", _MAGIC, _encode_lrec(0, len(buf))))
        self.handle.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        """Next record payload, or ``None`` at EOF.  A truncated/garbled
        record raises :class:`~mxnet_tpu.base.CorruptMessageError`
        unless ``skip_corrupt=True``, which counts it and resyncs to the
        next record boundary instead (see class doc)."""
        assert not self.writable
        if self._nh:
            _chaos.visit("data.read", name=self.uri)
            out = ctypes.POINTER(ctypes.c_char)()
            n = ctypes.c_size_t()
            r = self._nlib.mxtpu_recordio_reader_next(
                self._nh, ctypes.byref(out), ctypes.byref(n))
            if r == 1:
                return _native.buf_to_bytes(self._nlib, out, n.value)
            if r == 0:
                return None
            raise base.CorruptMessageError(
                "Invalid RecordIO magic number in %r" % self.uri)
        while True:
            start = self.handle.tell()
            try:
                return self._read_record()
            except base.CorruptMessageError:
                if not self.skip_corrupt:
                    # transactional read: the failed read leaves the
                    # cursor at the record start, so a caller-level
                    # retry (fit_stream's skip-and-count) re-reads the
                    # record instead of inheriting a mid-record cursor
                    # that would cascade misalignment errors forever
                    self.handle.seek(start)
                    raise
                self.skipped_corrupt += 1
                _M_CORRUPT.labels(os.path.basename(self.uri)).inc()
                if not self._resync(start + 4):
                    return None    # corruption ran into EOF: stream ends

    def _read_record(self):
        """One record from the Python handle; raises
        ``CorruptMessageError`` on any framing violation."""
        # reassemble continuation-framed records (kind 0 = whole record,
        # 1 = first part, 2 = middle, 3 = last) like the native reader
        parts = []
        while True:
            header = self.handle.read(8)
            if len(header) < 8:
                # clean EOF is exactly 0 bytes at a record boundary with no
                # continuation pending; anything else is a corrupt stream
                # (the native reader errors here too) — returning a partial
                # join / None would be silent data corruption
                if parts:
                    raise base.CorruptMessageError(
                        "truncated multi-part RecordIO record at EOF "
                        "in %r" % self.uri)
                if header:
                    raise base.CorruptMessageError(
                        "truncated RecordIO header at EOF (%d of 8 "
                        "bytes) in %r" % (len(header), self.uri))
                return None
            header = _chaos.visit("data.read", header, name=self.uri)
            magic, lrec = struct.unpack("<II", header)
            if magic != _MAGIC:
                raise base.CorruptMessageError(
                    "Invalid RecordIO magic number in %r" % self.uri)
            kind, length = _decode_lrec(lrec)
            payload = self.handle.read(length)
            if len(payload) < length:
                raise base.CorruptMessageError(
                    "truncated RecordIO payload (%d < %d bytes) in %r"
                    % (len(payload), length, self.uri))
            parts.append(payload)
            pad = (4 - length % 4) % 4
            if pad:
                self.handle.read(pad)
            if kind == 0 or kind == 3:
                return b"".join(parts)

    def _resync(self, pos):
        """Scan forward from ``pos`` (rounded up to 4-byte alignment —
        writers pad every record to 4 bytes) for the next magic word;
        leaves the handle at the record boundary.  False at EOF."""
        pos += (-pos) % 4
        self.handle.seek(pos)
        while True:
            word = self.handle.read(4)
            if len(word) < 4:
                return False
            if struct.unpack("<I", word)[0] == _MAGIC:
                self.handle.seek(-4, os.SEEK_CUR)
                return True


class MXIndexedRecordIO(MXRecordIO):
    """Indexed RecordIO with ``.idx`` sidecar (parity:
    ``recordio.py:MXIndexedRecordIO``)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            with open(idx_path) as fin:
                for line in fin.readlines():
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)

    def close(self):
        is_open = self.handle is not None or getattr(self, "_nh", None)
        if is_open and self.writable:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write("%s\t%d\n" % (str(key), self.idx[key]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.keys.append(key)
        self.idx[key] = pos


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a header + bytes into a record payload (parity: ``recordio.py:pack``)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    s = struct.pack(_IR_FORMAT, *header) + s
    return s


def unpack(s):
    """Unpack a record payload (parity: ``recordio.py:unpack``)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        header = header._replace(
            label=np.frombuffer(s[: header.flag * 4], dtype=np.float32))
        s = s[header.flag * 4 :]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array (parity: ``recordio.py:pack_img``; PNG/raw-npy
    encoding here since OpenCV isn't a dependency)."""
    from .image import imencode

    return pack(header, imencode(img, img_fmt=img_fmt, quality=quality))


def unpack_img(s, iscolor=-1):
    header, s = unpack(s)
    from .image import imdecode_bytes

    img = imdecode_bytes(s)
    return header, img
