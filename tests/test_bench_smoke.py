"""bench.py CPU smoke: the benchmark must keep its one-line JSON
contract (driver-parsed) in both per-step and BENCH_PIPELINE modes.
Tiny shapes + BENCH_STEPS=2 keep each subprocess a few seconds."""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(extra_env):
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_INNER="1",
               BENCH_STEPS="2", BENCH_BATCH="2", **extra_env)
    out = subprocess.run([sys.executable, os.path.join(_REPO, "bench.py")],
                         env=env, capture_output=True, text=True,
                         timeout=240, cwd=_REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert lines, out.stdout
    return json.loads(lines[-1])


@pytest.mark.parametrize("pipeline", [1, 4])
def test_bench_json_contract(pipeline):
    rec = _run_bench({"BENCH_PIPELINE": str(pipeline)})
    assert rec["metric"] == "resnet8_cpu_smoke_throughput"
    assert rec["unit"] == "img/s"
    assert rec["value"] > 0
    assert rec["vs_baseline"] > 0
    # additive observability keys (same contract, new fields)
    assert rec["step_ms_p50"] > 0
    assert rec["step_ms_p99"] >= rec["step_ms_p50"]
    assert rec["tokens_per_sec"] > 0
    # additive observability counters: a clean bench fires no chaos and
    # drops no spans, but the keys must always be present
    assert rec["chaos_fired_total"] == 0
    assert rec["spans_dropped_total"] == 0
    # additive provenance keys: schema revision + the commit measured
    assert rec["schema_version"] >= 3
    assert isinstance(rec["git_sha"], str) and rec["git_sha"]
    # pipeline_steps only appears when the pipelined path actually ran
    if pipeline > 1:
        assert rec["pipeline_steps"] == pipeline
    else:
        assert "pipeline_steps" not in rec


def test_bench_serving_keys():
    """BENCH_SERVING=1: the schema-5 serving keys, the >= 2x continuous-
    batching acceptance floor over the batch-1 sequential baseline, and
    the zero-recompiles-after-warmup steady-state contract."""
    rec = _run_bench({"BENCH_SERVING": "1", "BENCH_REQUESTS": "128"})
    assert rec["schema_version"] >= 5
    assert rec["metric"] == "serving_cpu_smoke_throughput"
    assert rec["unit"] == "req/s"
    assert rec["requests_per_sec"] > 0
    assert rec["request_ms_p99"] >= rec["request_ms_p50"] > 0
    assert 0.0 < rec["batch_occupancy"] <= 1.0
    assert rec["recompiles_after_warmup"] == 0
    assert rec["requests_per_sec"] >= 2.0 * rec["requests_per_sec_sequential"], (
        "continuous batching lost its edge: %.1f vs sequential %.1f req/s"
        % (rec["requests_per_sec"], rec["requests_per_sec_sequential"]))


def test_bench_generate_keys():
    """BENCH_GENERATE=1: the schema-10 generation keys and the >= 2x
    acceptance floor over the naive re-prefill-per-token baseline."""
    rec = _run_bench({"BENCH_GENERATE": "1", "BENCH_GEN_TOKENS": "16",
                      "BENCH_GEN_USERS": "4"})
    assert rec["schema_version"] >= 10
    assert rec["metric"] == "generation_cpu_smoke_throughput"
    assert rec["unit"] == "tokens/s"
    assert rec["tokens_per_sec"] > 0
    assert rec["tokens_per_sec_per_user"] > 0
    assert rec["inter_token_ms_p99"] > 0
    assert rec["prefill_ms_p50"] > 0
    assert 0.0 < rec["kv_cache_occupancy"] <= 1.0
    assert rec["recompiles_after_warmup"] == 0
    assert rec["tokens_per_sec"] >= 2.0 * rec["tokens_per_sec_naive"], (
        "the paged-cache decode lane lost its edge: %.1f vs naive "
        "%.1f tokens/s"
        % (rec["tokens_per_sec"], rec["tokens_per_sec_naive"]))


def test_bench_wire_keys():
    """BENCH_WIRE=1: the schema-11 wire keys are present and >0 on the
    CPU smoke, the schema-13 additions (compression ratio, coalesced
    RPC savings) are live under the lane's default PR-17 stack, and
    the byte books reconcile with the socket truth (the lane's
    falsifiability gate rides in the JSON row)."""
    rec = _run_bench({"BENCH_WIRE": "1"})
    assert rec["schema_version"] >= 13
    assert rec["metric"] == "kv_wire_bytes_per_step"
    assert rec["unit"] == "B/step"
    assert rec["kv_bytes_per_step"] > 0
    assert rec["kv_header_overhead_pct"] > 0
    assert rec["kv_codec_ms_share"] > 0
    assert rec["kv_rpcs_per_flush_p50"] > 0
    # the lane defaults to int8 push compression + coalescing, so both
    # schema-13 keys must show real wins, not placeholders
    assert rec["kv_compress_ratio"] > 1.0
    assert rec["kv_coalesce_rpcs_saved"] > 0
    assert rec["wire_reconciles"] is True
    assert rec["codec_reconciles"] is True


def test_bench_snapshot_keys():
    """BENCH_SNAPSHOT=1: the schema-14 durability keys — save, frozen
    window, and cold-restore-onto-3-shards latencies — all live on the
    CPU smoke, with the re-stripe round-trip asserted inside the lane."""
    rec = _run_bench({"BENCH_SNAPSHOT": "1", "BENCH_SNAPSHOT_KEYS": "8",
                      "BENCH_SNAPSHOT_PUSHES": "64"})
    assert rec["schema_version"] >= 14
    assert rec["metric"] == "snapshot_save"
    assert rec["unit"] == "ms"
    assert rec["snapshot_save_ms"] > 0
    assert rec["snapshot_restore_ms"] > 0
    # the frozen window is the delta cut only — it must be a fraction
    # of the full save, or the two-phase design has regressed into a
    # stop-the-world snapshot
    assert 0 < rec["snapshot_frozen_ms"] < rec["snapshot_save_ms"]
    assert rec["snapshot_restripe_ok"] is True


def test_bench_kernels_keys():
    """BENCH_KERNELS=1: the schema-15 fused-kernel keys.  Parity is the
    gate (the lane exits nonzero without it, so returncode==0 already
    proves the quick grid is green); the optimizer pair must show the
    fused tree's measured CPU win over the eager per-param dispatch —
    the one kernel claim this lane is allowed to make off-TPU."""
    rec = _run_bench({"BENCH_KERNELS": "1", "BENCH_KERNEL_REPS": "5"})
    assert rec["schema_version"] >= 15
    assert rec["metric"] == "kernels_parity"
    assert rec["unit"] == "ok"
    assert rec["fused_parity_ok"] is True
    assert rec["fused_parity_cases"] > 0
    assert rec["attn_prefill_ms"] > 0
    assert rec["paged_decode_tokens_per_sec"] > 0
    assert rec["fused_opt_step_ms"] > 0
    assert rec["stock_opt_step_ms"] > 0
    # the measured CPU claim: one jitted fused tree step beats O(n)
    # eager per-param updates
    assert rec["fused_opt_step_ms"] < rec["stock_opt_step_ms"]
    # per-variant compile-FLOPs rows (attention variants gate on these,
    # not on CPU wall time)
    assert isinstance(rec["variant_compile_flops"], dict)


def test_bench_fairness_keys():
    """BENCH_FAIRNESS=1: the schema-12 multi-tenant keys — isolation
    ratio, quota shed rate, KV-affinity hit ratio — all live and
    bounded on the CPU smoke."""
    rec = _run_bench({"BENCH_FAIRNESS": "1", "BENCH_FAIR_REQUESTS": "32"})
    assert rec["schema_version"] >= 12
    assert rec["metric"] == "fairness_cpu_smoke_throughput"
    assert rec["unit"] == "req/s"
    assert rec["value"] > 0
    assert rec["fairness_p99_ratio"] > 0
    assert 0.0 <= rec["quota_shed_rate"] <= 1.0
    assert rec["kv_affinity_hit_ratio"] > 0


def test_bench_git_sha_override():
    rec = _run_bench({"BENCH_GIT_SHA": "cafef00d"})
    assert rec["git_sha"] == "cafef00d"


def test_bench_vs_baseline_published():
    """Fresh bench number vs the BASELINE.json published reference for
    the SAME metric.  The tolerance is deliberately generous (8x): this
    guards against the bench silently measuring nothing (zeros, wrong
    units, dead path), not against hardware variance between
    containers."""
    with open(os.path.join(_REPO, "BASELINE.json")) as f:
        published = json.load(f).get("published", {})
    rec = _run_bench({})
    ref = published.get(rec["metric"])
    if not ref:
        pytest.skip("no published baseline for metric %r" % rec["metric"])
    assert rec["value"] >= float(ref["value"]) / 8.0, (
        "bench %s=%.2f collapsed vs published %.2f"
        % (rec["metric"], rec["value"], ref["value"]))
    assert rec["unit"] == ref["unit"]
