"""Embedded-runtime bridge for the full C API (parity: reference
``include/mxnet/c_api.h`` — Symbol ``:645`` MXSymbolCreateFromJSON,
Executor ``:1066`` MXExecutorBindEX, KVStore ``:1207`` MXKVStoreCreate,
DataIter ``:1292`` MXDataIterCreateIter).

``native/src/c_api_full.cc`` embeds CPython and calls these flat
functions with primitive arguments only (int64 handles, UTF-8 strings,
raw float32 buffers), keeping the C++ layer thin.  Objects live in a
registry keyed by integer handles — the C side never touches PyObjects.

All functions raise on error; the C layer converts the exception text to
``mxtpu_capi_last_error``.
"""

from __future__ import annotations

import json

import numpy as _np

_objects = {}
_next_handle = 1


def _register(obj):
    global _next_handle
    handle = _next_handle
    _next_handle += 1
    _objects[handle] = obj
    return handle


def _get(handle):
    try:
        return _objects[handle]
    except KeyError:
        raise ValueError("invalid or freed handle %d" % handle)


def free(handle):
    _objects.pop(handle, None)
    return 0


def _mx():
    import mxnet_tpu

    return mxnet_tpu


def _parse_kwargs(kwargs_json):
    return json.loads(kwargs_json) if kwargs_json else {}


def _to_array(shape, buf):
    """shape: int sequence (the C side passes a Python list)."""
    return _np.frombuffer(buf, dtype=_np.float32).reshape(tuple(shape)).copy()


def _from_array(arr):
    """Returns (shape, buffer-protocol object).  The C side reads the
    payload via PyObject_GetBuffer — handing back the numpy array itself
    (not ``.tobytes()``) saves one full copy per crossing (the r3 verdict's
    'full-copy float32 marshalling' ceiling).  MXTPU_MARSHAL_BYTES=1
    restores the r3 bytes-object path (perf A/B diagnostic, docs/PERF.md)."""
    import os

    arr = _np.ascontiguousarray(_np.asarray(arr), dtype=_np.float32)
    if os.environ.get("MXTPU_MARSHAL_BYTES") == "1":
        return [int(d) for d in arr.shape], arr.tobytes()
    return [int(d) for d in arr.shape], arr


# ---------------- Symbol ----------------

def sym_create_variable(name):
    return _register(_mx().sym.Variable(name))


def sym_create_atomic(op_name, kwargs_json):
    """Deferred atomic symbol (reference MXSymbolCreateAtomicSymbol):
    parameters now, inputs at compose time."""
    if not hasattr(_mx().sym, op_name):
        raise ValueError("unknown operator %r" % op_name)
    return _register(("__atomic__", op_name, _parse_kwargs(kwargs_json)))


def sym_compose(handle, name, arg_names, arg_handles):
    """Wire inputs into an atomic symbol (reference MXSymbolCompose).
    Mutates the handle to hold the composed symbol, like the reference.
    ``arg_names``/``arg_handles``: lists (the C side) or JSON strings."""
    entry = _get(handle)
    if not (isinstance(entry, tuple) and entry[0] == "__atomic__"):
        raise ValueError("handle is not an un-composed atomic symbol")
    _, op_name, params = entry
    if isinstance(arg_names, str):
        arg_names = json.loads(arg_names)
    if isinstance(arg_handles, str):
        arg_handles = json.loads(arg_handles)
    inputs = {n: _get(h) for n, h in zip(arg_names, arg_handles)}
    kwargs = dict(params)
    kwargs.update(inputs)
    if name:
        kwargs["name"] = name
    _objects[handle] = getattr(_mx().sym, op_name)(**kwargs)
    return 0


def sym_from_json(text):
    return _register(_mx().sym.load_json(text))


def sym_to_json(handle):
    return _get(handle).tojson()


def sym_list(handle, which):
    sym = _get(handle)
    if which == "arguments":
        return json.dumps(sym.list_arguments())
    if which == "outputs":
        return json.dumps(sym.list_outputs())
    if which == "auxiliary_states":
        return json.dumps(sym.list_auxiliary_states())
    raise ValueError("unknown listing %r" % which)


def sym_infer_shape(handle, shapes_json):
    """arg/out/aux shapes from input shapes (reference MXSymbolInferShape)."""
    shapes = {k: tuple(v) for k, v in json.loads(shapes_json).items()}
    arg, out, aux = _get(handle).infer_shape(**shapes)
    return json.dumps({"arg": [list(s) for s in arg],
                       "out": [list(s) for s in out],
                       "aux": [list(s) for s in aux]})


# ---------------- Executor ----------------

def executor_simple_bind(sym_handle, shapes_json, grad_req):
    mx = _mx()
    shapes = {k: tuple(v) for k, v in json.loads(shapes_json).items()}
    ex = _get(sym_handle).simple_bind(mx.cpu() if _cpu_only()
                                      else mx.context.current_context(),
                                      grad_req=grad_req, **shapes)
    return _register(ex)


def _cpu_only():
    import jax

    return jax.default_backend() == "cpu"


def executor_forward(ex_handle, is_train):
    ex = _get(ex_handle)
    ex.forward(is_train=bool(is_train))
    if not is_train:
        ex.outputs  # materialize eagerly: C callers read outputs next
    return 0


def executor_backward(ex_handle):
    _get(ex_handle).backward()
    return 0


def executor_num_outputs(ex_handle):
    return len(_get(ex_handle).outputs)


def executor_output(ex_handle, index):
    return _from_array(_get(ex_handle).outputs[index].asnumpy())


def _executor_dict(ex, kind):
    if kind == "arg":
        return ex.arg_dict
    if kind == "grad":
        return ex.grad_dict
    if kind == "aux":
        return ex.aux_dict
    raise ValueError("unknown array kind %r (arg/grad/aux)" % kind)


def executor_get_array(ex_handle, kind, name):
    d = _executor_dict(_get(ex_handle), kind)
    if name not in d or d[name] is None:
        raise KeyError("no %s array %r" % (kind, name))
    return _from_array(d[name].asnumpy())


def executor_set_array(ex_handle, kind, name, shape, buf):
    d = _executor_dict(_get(ex_handle), kind)
    if name not in d or d[name] is None:
        raise KeyError("no %s array %r" % (kind, name))
    d[name][:] = _to_array(shape, buf)
    return 0


# ---------------- KVStore ----------------

def kvstore_create(kind):
    return _register(_mx().kv.create(kind))


def kvstore_init(kv_handle, key, shape, buf):
    _get(kv_handle).init(key, _mx().nd.array(_to_array(shape, buf)))
    return 0


def kvstore_push(kv_handle, key, shape, buf):
    _get(kv_handle).push(key, _mx().nd.array(_to_array(shape, buf)))
    return 0


def kvstore_pull(kv_handle, key, shape):
    mx = _mx()
    out = mx.nd.zeros(tuple(shape))
    _get(kv_handle).pull(key, out=out)
    return _from_array(out.asnumpy())


def kvstore_set_optimizer(kv_handle, name, kwargs_json):
    opt = _mx().optimizer.create(name, **_parse_kwargs(kwargs_json))
    _get(kv_handle).set_optimizer(opt)
    return 0


def kvstore_rank(kv_handle):
    return _get(kv_handle).rank


def kvstore_num_workers(kv_handle):
    return _get(kv_handle).num_workers


def kvstore_type(kv_handle):
    return _get(kv_handle).type


# ---------------- DataIter ----------------

def dataiter_create(type_name, kwargs_json):
    """Create an iterator by registry name with JSON kwargs (reference
    MXDataIterCreateIter's string-kwarg contract).  Shape-like values may
    be JSON lists; they arrive as python lists and the iterators accept
    tuples, so convert one level."""
    io = _mx().io
    if not hasattr(io, type_name):
        raise ValueError("unknown data iterator %r" % type_name)
    kwargs = {}
    for k, v in _parse_kwargs(kwargs_json).items():
        kwargs[k] = tuple(v) if isinstance(v, list) else v
    return _register(getattr(io, type_name)(**kwargs))


def dataiter_next(it_handle):
    return 1 if _get(it_handle).iter_next() else 0


def dataiter_reset(it_handle):
    _get(it_handle).reset()
    return 0


def dataiter_data(it_handle):
    return _from_array(_get(it_handle).getdata()[0].asnumpy())


def dataiter_label(it_handle):
    labels = _get(it_handle).getlabel()
    if not labels:
        raise ValueError("iterator provides no label")
    return _from_array(labels[0].asnumpy())


def dataiter_pad(it_handle):
    return int(_get(it_handle).getpad() or 0)


def executor_save_checkpoint(ex_handle, sym_handle, prefix, epoch):
    """Write the Python-compatible two-file checkpoint (reference
    save_checkpoint format: prefix-symbol.json + prefix-%04d.params with
    arg:/aux: prefixed names) from an executor's current state — a
    C/C++-trained model loads straight into mx.model.load_checkpoint."""
    mx = _mx()
    ex = _get(ex_handle)
    sym = _get(sym_handle)
    # data/label inputs are not parameters: exclude them, like Module does
    data_like = {name for name in sym.list_arguments()
                 if name == "data" or name.endswith("_label")}
    args = {k: v for k, v in ex.arg_dict.items()
            if v is not None and k not in data_like}
    auxs = {k: v for k, v in ex.aux_dict.items() if v is not None}
    mx.model.save_checkpoint(prefix, int(epoch), sym, args, auxs)
    # the params write rides the engine's IO lane; wait_for_checkpoint is
    # the documented read-after-write barrier (model.py) — nd.waitall only
    # syncs the device, not engine IO
    mx.model.wait_for_checkpoint("%s-%04d.params" % (prefix, int(epoch)))
    return 0


def executor_load_params(ex_handle, path):
    """Load a .params file (arg:/aux: prefixed) into a bound executor."""
    mx = _mx()
    ex = _get(ex_handle)
    mx.model.wait_for_checkpoint(path)  # read-after-IO-lane-write barrier
    for key, value in mx.nd.load(path).items():
        kind, _, name = key.partition(":")
        if not name or kind not in ("arg", "aux"):
            raise ValueError(
                "%s: key %r is not the checkpoint format (expected "
                "'arg:<name>' or 'aux:<name>' entries)" % (path, key))
        d = ex.arg_dict if kind == "arg" else ex.aux_dict
        if name in d and d[name] is not None:
            d[name][:] = value
    return 0


# ---------------- imperative / autograd / dtyped NDArray tier ----------
# Parity: reference MXImperativeInvoke (src/c_api/c_api_ndarray.cc:322)
# and MXAutograd* (include/mxnet/c_api.h) — device arrays live in this
# registry as handles; the host side crosses dtype-tagged raw bytes.

_DTYPE_BY_CODE = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
                  4: "int32", 5: "int8", 6: "int64", 7: "bfloat16"}
_CODE_BY_DTYPE = {v: k for k, v in _DTYPE_BY_CODE.items()}


def _np_dtype(code):
    name = _DTYPE_BY_CODE[int(code)]
    if name == "bfloat16":
        import ml_dtypes

        return _np.dtype(ml_dtypes.bfloat16)
    return _np.dtype(name)


def nd_to_device(shape, buf, dtype_code):
    """(shape, raw bytes, dtype code) -> device NDArray handle."""
    arr = _np.frombuffer(buf, dtype=_np_dtype(dtype_code)) \
        .reshape(tuple(shape)).copy()
    return _register(_mx().nd.array(arr, dtype=arr.dtype))


def nd_from_device(handle):
    """Device NDArray handle -> (shape, buffer, dtype code), lossless."""
    arr = _np.ascontiguousarray(_get(handle).asnumpy())
    code = _CODE_BY_DTYPE.get(str(arr.dtype))
    if code is None:
        raise TypeError("dtype %s has no MXTPU_DTYPE code" % arr.dtype)
    return [int(d) for d in arr.shape], arr, code


def imperative_invoke(op_name, kwargs_json, in_handles):
    """Run a registry op imperatively on device arrays; returns the list
    of output handles.  Taped automatically when autograd recording is on
    (ndarray.invoke's contrib.autograd hook)."""
    from mxnet_tpu import ndarray as _ndmod
    from mxnet_tpu.contrib import autograd as _ag

    args = [_get(h) for h in in_handles]
    out = _ndmod.invoke(op_name, args, _parse_kwargs(kwargs_json),
                        is_train=_ag.is_training())
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    return [_register(o) for o in outs]


def autograd_set_recording(on):
    from mxnet_tpu.contrib import autograd as _ag

    _ag.set_is_training(bool(on))
    return 0


def autograd_mark_variables(var_handles):
    """Mark device arrays as differentiable; returns one zero-initialized
    gradient handle per variable (filled by autograd_backward)."""
    import jax.numpy as jnp

    from mxnet_tpu.contrib import autograd as _ag
    from mxnet_tpu.ndarray import NDArray

    variables = [_get(h) for h in var_handles]
    grads = [NDArray(jnp.zeros_like(v._data), v._ctx) for v in variables]
    _ag.mark_variables(variables, grads)
    return [_register(g) for g in grads]


def autograd_backward(out_handles):
    from mxnet_tpu.contrib import autograd as _ag

    _ag.compute_gradient([_get(h) for h in out_handles])
    return 0
