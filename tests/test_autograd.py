"""Imperative autograd (parity model: reference
``tests/python/unittest/test_contrib_autograd.py``)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.contrib import autograd as ag
from mxnet_tpu.test_utils import assert_almost_equal


def autograd_assert(*args, **kwargs):
    func = kwargs["func"]
    grad_f = kwargs["grad_func"]
    argnum = kwargs.get("argnum", None)
    grad_func = ag.grad_and_loss(func, argnum)
    grad_vals, output = grad_func(*args)
    res = func(*args)
    assert_almost_equal(output.asnumpy(), res.asnumpy())
    grad_res = grad_f(*args)
    assert len(grad_vals) == len(grad_res)
    for a, b in zip(grad_vals, grad_res):
        assert_almost_equal(a.asnumpy(), b.asnumpy(), rtol=1e-4)


def test_unary_func():
    x = mx.nd.uniform(shape=(4, 5))
    autograd_assert(x, func=lambda x: x + 1,
                    grad_func=lambda x: [mx.nd.ones((4, 5))])
    autograd_assert(x, func=lambda x: x * x,
                    grad_func=lambda x: [x * 2])


def test_binary_func():
    x = mx.nd.uniform(shape=(4, 5))
    y = mx.nd.uniform(shape=(4, 5))
    autograd_assert(x, y, func=lambda x, y: x * y,
                    grad_func=lambda x, y: [y, x])


def test_argnum():
    def f_with_mode(a, b, mode):
        if mode:
            return a + b
        return a * b

    a = mx.nd.uniform(shape=(3, 2))
    b = mx.nd.uniform(shape=(3, 2))
    autograd_assert(a, b, True, argnum=[0, 1],
                    func=f_with_mode,
                    grad_func=lambda a, b, m: [mx.nd.ones((3, 2)),
                                               mx.nd.ones((3, 2))])


def test_training_scope():
    assert not ag.is_training()
    with ag.train_section():
        assert ag.is_training()
        with ag.test_section():
            assert not ag.is_training()
        assert ag.is_training()
    assert not ag.is_training()


def test_grad_and_loss_chain():
    def f(x):
        return mx.nd.sum(mx.nd.exp(x) * x)

    x_np = np.random.uniform(-1, 1, (3, 4)).astype(np.float32)
    x = mx.nd.array(x_np)
    grads, loss = ag.grad_and_loss(f)(x)
    expect = np.exp(x_np) * x_np + np.exp(x_np)
    assert_almost_equal(grads[0].asnumpy(), expect, rtol=1e-4)
    assert_almost_equal(loss.asnumpy(), np.sum(np.exp(x_np) * x_np),
                        rtol=1e-4)


def test_mark_variables_compute_gradient():
    x = mx.nd.array(np.random.uniform(-1, 1, (3, 4)).astype(np.float32))
    gx = mx.nd.zeros((3, 4))
    ag.mark_variables([x], [gx])
    with ag.train_section():
        y = mx.nd.sum(x * x)
        ag.compute_gradient([y])
    assert_almost_equal(gx.asnumpy(), 2 * x.asnumpy(), rtol=1e-4)


def test_reflected_ops_under_training():
    """__rsub__/__rdiv__ keep operand order on the taped path."""
    a_np = np.array([1.0, 2.0, 3.0], np.float32)
    a = mx.nd.array(a_np)
    with ag.train_section():
        r1 = (10.0 - a).asnumpy()
        r2 = (12.0 / a).asnumpy()
        r3 = (a + np.ones(3, np.float32)).asnumpy()  # array operand
    assert_almost_equal(r1, 10.0 - a_np)
    assert_almost_equal(r2, 12.0 / a_np, rtol=1e-5)
    assert_almost_equal(r3, a_np + 1.0)
