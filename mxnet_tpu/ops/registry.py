"""Operator registry — the NNVM ``Op`` registry rebuilt for XLA.

In the reference, ops live in two C++ registries (``OperatorProperty`` and NNVM
``FCompute``; reference ``include/mxnet/op_attr_types.h:57-62``,
``src/nnvm/legacy_op_util.cc``) and kernels are mshadow/CUDA.  Here there is a
single registry and every op's compute function is a *traceable JAX function*:
the imperative path jits it per (attrs, shapes) and the symbolic executor traces
whole graphs of them into one XLA computation.  That one design change replaces
the dependency engine + mshadow + cuDNN stack: XLA does the scheduling, fusion
and memory planning that the reference does by hand.

An op declares:

* ``arg_names``   — positional tensor inputs (e.g. ``['data','weight','bias']``);
  missing inputs auto-materialize as variables at Symbol compose time, exactly
  like the reference's parameter inputs.
* ``aux_names``   — auxiliary states mutated by training forward (BatchNorm
  moving stats).  The compute fn returns their new values after the outputs.
* ``params``      — attribute spec (name -> ParamSpec), the ``dmlc::Parameter``
  equivalent: typed, defaulted, string-parseable (for JSON graph loading).
* ``fn(attrs, *tensors, is_train=..., rng=...)`` — the compute rule on jax
  arrays.  ``rng`` is a jax PRNG key for stochastic ops (Dropout, samplers).
"""

from __future__ import annotations

import ast
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as _np

from ..base import MXNetError

__all__ = ["Op", "ParamSpec", "register", "get_op", "list_ops", "OP_REGISTRY",
           "OpVariant", "FUSED_VARIANTS", "register_variant", "fused_enabled",
           "select_variant", "dispatch_variant", "fused_fallbacks",
           "reset_fused_dispatch"]

OP_REGISTRY: Dict[str, "Op"] = {}
_ALIAS: Dict[str, str] = {}


def _parse_bool(s):
    if isinstance(s, bool):
        return s
    if isinstance(s, (int, float)):
        return bool(s)
    s = s.strip().lower()
    if s in ("true", "1"):
        return True
    if s in ("false", "0"):
        return False
    raise ValueError("cannot parse bool from %r" % s)


def _parse_shape(s):
    if s is None:
        return None
    if isinstance(s, (tuple, list)):
        return tuple(int(x) for x in s)
    if isinstance(s, (int, _np.integer)):
        return (int(s),)
    s = s.strip()
    if s in ("None", ""):
        return None
    val = ast.literal_eval(s)
    if isinstance(val, (int, float)):
        return (int(val),)
    return tuple(int(x) for x in val)


class ParamSpec:
    """One attribute of an op (the ``DMLC_DECLARE_FIELD`` equivalent)."""

    __slots__ = ("name", "type", "default", "required", "enum")

    def __init__(self, type="str", default=None, required=False, enum=None):
        self.type = type
        self.default = default
        self.required = required
        self.enum = enum

    def parse(self, value):
        if value is None:
            return None
        t = self.type
        if t == "int":
            return int(value)
        if t == "float":
            return float(value)
        if t == "bool":
            return _parse_bool(value)
        if t == "shape":
            return _parse_shape(value)
        if t == "str":
            v = str(value)
            if self.enum is not None and v not in self.enum:
                raise MXNetError("invalid value %r; expected one of %s" % (v, self.enum))
            return v
        if t == "any":
            return value
        raise MXNetError("unknown param type %r" % (t,))


class Op:
    """A registered operator."""

    def __init__(
        self,
        name: str,
        fn: Callable,
        arg_names: Sequence[str] = ("data",),
        aux_names: Sequence[str] = (),
        num_outputs=1,
        params: Optional[Dict[str, ParamSpec]] = None,
        needs_mode: bool = False,
        needs_rng: bool = False,
        variable_args: bool = False,
        output_names: Optional[Sequence[str]] = None,
        input_names_fn: Optional[Callable] = None,
        collect_extra: bool = False,
        mesh_aware: bool = False,
    ):
        self.name = name
        self.fn = fn
        self.arg_names = list(arg_names)
        self.aux_names = list(aux_names)
        self.num_outputs = num_outputs  # int or callable(attrs) -> int
        self.params = params or {}
        self.needs_mode = needs_mode
        self.needs_rng = needs_rng
        # variable_args: op takes N homogeneous inputs (Concat, add_n, ...)
        # controlled by attr 'num_args'
        self.variable_args = variable_args
        self.output_names = list(output_names) if output_names else None
        self.input_names_fn = input_names_fn
        self.collect_extra = collect_extra
        # mesh_aware: the compute rule consults the ambient default mesh at
        # trace time, so jit caches must key on the mesh identity too
        self.mesh_aware = mesh_aware

    # -- attrs ---------------------------------------------------------
    def parse_attrs(self, kwargs: Dict) -> Dict:
        """Validate/parse keyword attributes into a canonical attrs dict."""
        attrs = {}
        for k, v in kwargs.items():
            if k in self.params:
                attrs[k] = self.params[k].parse(v)
            elif k == "num_args" and self.variable_args:
                attrs["num_args"] = int(v)
            elif self.collect_extra:
                attrs.setdefault("_kwargs", {})[k] = v
            else:
                raise MXNetError(
                    "%s got unknown attribute %r (known: %s)"
                    % (self.name, k, sorted(self.params))
                )
        for k, spec in self.params.items():
            if k not in attrs:
                if spec.required:
                    raise MXNetError("%s missing required attribute %r" % (self.name, k))
                attrs[k] = spec.default
        return attrs

    def attrs_key(self, attrs: Dict):
        """Hashable canonical form of attrs (jit-cache key component)."""
        return tuple(sorted((k, _hashable(v)) for k, v in attrs.items()))

    def n_outputs(self, attrs) -> int:
        if callable(self.num_outputs):
            return self.num_outputs(attrs)
        return self.num_outputs

    def input_names(self, attrs) -> List[str]:
        if self.variable_args:
            n = int(attrs.get("num_args") or 0)
            return ["arg%d" % i for i in range(n)]
        if self.input_names_fn is not None:
            return list(self.input_names_fn(attrs))
        return self.arg_names

    # -- compute -------------------------------------------------------
    def apply(self, attrs, args, auxs=(), is_train=False, rng=None):
        """Run the compute rule.  Returns (outputs_list, new_aux_list).

        When the fused tier (``MXNET_TPU_OPS_FUSED``) selects a variant
        for this op, the variant's compute rule runs instead — same
        ``(attrs, *tensors)`` convention.  A variant that raises at
        dispatch falls back to the stock rule and is booked out of
        selection for the rest of the process (exactly one
        ``ops_fused_fallback_total`` increment + ops event per
        (op, variant))."""
        kw = {}
        if self.needs_mode:
            kw["is_train"] = is_train
        if self.needs_rng:
            kw["rng"] = rng
        tensors = list(args) + list(auxs)
        var = select_variant(self.name)
        if var is not None:
            try:
                _chaos_visit(self.name, var.name)
                out = var.fn(attrs, *tensors, **kw)
            except Exception as exc:  # noqa: BLE001 — fallback seam
                _record_fused_fallback(self.name, var.name, exc)
                out = self.fn(attrs, *tensors, **kw)
        else:
            out = self.fn(attrs, *tensors, **kw)
        n_out = self.n_outputs(attrs)
        if not isinstance(out, tuple):
            out = (out,)
        outputs = list(out[:n_out])
        new_aux = list(out[n_out:])
        if len(outputs) != n_out or len(new_aux) != len(self.aux_names):
            raise MXNetError(
                "%s returned %d arrays; expected %d outputs + %d aux"
                % (self.name, len(out), n_out, len(self.aux_names))
            )
        return outputs, new_aux

    def __repr__(self):
        return "Op(%s)" % self.name


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


def register(name, aliases=(), **kwargs):
    """Decorator: register ``fn`` as op ``name`` (+ aliases)."""

    def deco(fn):
        op = Op(name, fn, **kwargs)
        OP_REGISTRY[name] = op
        for a in aliases:
            _ALIAS[a] = name
        return fn

    return deco


def register_op(op: Op, aliases=()):
    OP_REGISTRY[op.name] = op
    for a in aliases:
        _ALIAS[a] = op.name
    return op


def get_op(name: str) -> Op:
    if name in OP_REGISTRY:
        return OP_REGISTRY[name]
    if name in _ALIAS:
        return OP_REGISTRY[_ALIAS[name]]
    raise MXNetError("operator %r is not registered" % name)


def list_ops() -> List[str]:
    return sorted(set(OP_REGISTRY) | set(_ALIAS))


# ---------------------------------------------------------------------------
# fused-kernel variant tier (ops/fused/) — the dispatch seam
# ---------------------------------------------------------------------------
#
# Each op (a registry ``Op`` OR a functional hot path like
# ``paged_decode_attention``) may carry named *variants*: ``stock`` is
# the implementation that already lives in the op module, anything else
# is a Pallas kernel / hand-fused jitted composite registered by
# ``mxnet_tpu.ops.fused``.  Selection is per jax backend with a global
# kill-switch (``MXNET_TPU_OPS_FUSED=0`` → stock everywhere,
# bit-identical to a tree without this tier) and a per-op override
# (``MXNET_TPU_OPS_FUSED_OVERRIDE="LayerNorm=fused,sgd_mom_update=stock"``
# — forces a named variant regardless of its backend eligibility, or
# forces stock).  A variant that raises at dispatch falls back to stock
# and is booked out of selection: exactly one
# ``ops_fused_fallback_total{op,reason}`` increment, one
# ``ops.fused.fallback`` event, per (op, variant).  The ``ops.fused``
# chaos site is visited on every variant dispatch (``drop`` forces the
# fallback path; ``corrupt`` is consumed by the parity harness, which
# routes variant output bytes through the site).


class OpVariant:
    """One named implementation of an op in the fused tier.

    ``fn`` follows the *op convention* ``fn(attrs, *tensors)`` when the
    name is a registry op dispatched through :meth:`Op.apply`, and the
    *plain convention* ``fn(*args, **kwargs)`` when dispatched through
    :func:`dispatch_variant` (functional hot paths).  ``backends`` is
    the tuple of jax platforms the variant is eligible on by default;
    ``parity`` is the contract class the parity harness asserts —
    ``"bitwise"`` (output bits equal stock's) or ``"tolerance"``
    (dtype-classed allclose; reduction reorder allowed).
    """

    __slots__ = ("op_name", "name", "fn", "backends", "parity")

    def __init__(self, op_name, name, fn, backends=("tpu",),
                 parity="bitwise"):
        if parity not in ("bitwise", "tolerance"):
            raise MXNetError("variant parity must be 'bitwise' or "
                             "'tolerance', got %r" % (parity,))
        if name == "stock":
            raise MXNetError("'stock' names the built-in path; register "
                             "variants under another name")
        self.op_name = op_name
        self.name = name
        self.fn = fn
        self.backends = tuple(backends)
        self.parity = parity

    def __repr__(self):
        return "OpVariant(%s:%s)" % (self.op_name, self.name)


#: op name -> {variant name -> OpVariant}, in registration order.
FUSED_VARIANTS: Dict[str, Dict[str, OpVariant]] = {}

_FUSED_LOCK = threading.Lock()
_FUSED_FAILED: Dict = {}        # (op, variant) -> reason class name
_FUSED_BACKEND = []             # cached jax.default_backend()
_OVERRIDE_CACHE = [None, {}]    # [env string, parsed dict]
_FALLBACK_FAMILY = []           # lazily registered counter family


def register_variant(op_name, variant, fn=None, backends=("tpu",),
                     parity="bitwise"):
    """Register ``fn`` as variant ``variant`` of op ``op_name``.

    Usable directly or as a decorator.  The graftcheck ``fused-parity``
    rule requires every call site to pass LITERAL op/variant names and
    to have a matching ``register_parity`` registration
    (``mxnet_tpu/ops/fused/parity.py``)."""
    def deco(f):
        var = OpVariant(op_name, variant, f, backends=backends,
                        parity=parity)
        with _FUSED_LOCK:
            FUSED_VARIANTS.setdefault(op_name, {})[variant] = var
        return f

    if fn is not None:
        return deco(fn)
    return deco


def fused_enabled():
    """The tier kill-switch: ``MXNET_TPU_OPS_FUSED`` (default on).
    ``0`` restores stock behavior everywhere, bit for bit."""
    return os.environ.get("MXNET_TPU_OPS_FUSED", "1").strip().lower() \
        not in ("0", "false", "off", "no")


def _fused_override():
    """``MXNET_TPU_OPS_FUSED_OVERRIDE="op=variant,..."`` parsed + cached
    per env value (``variant`` = ``stock`` forces the built-in path)."""
    env = os.environ.get("MXNET_TPU_OPS_FUSED_OVERRIDE")
    if env != _OVERRIDE_CACHE[0]:
        parsed = {}
        for part in (env or "").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise MXNetError(
                    "MXNET_TPU_OPS_FUSED_OVERRIDE entry %r: need "
                    "op=variant" % part)
            k, v = part.split("=", 1)
            parsed[k.strip()] = v.strip()
        with _FUSED_LOCK:
            _OVERRIDE_CACHE[0] = env
            _OVERRIDE_CACHE[1] = parsed
    return _OVERRIDE_CACHE[1]


def _backend():
    if not _FUSED_BACKEND:
        import jax

        _FUSED_BACKEND.append(jax.default_backend())
    return _FUSED_BACKEND[0]


def select_variant(op_name) -> Optional[OpVariant]:
    """The dispatch decision for one op: the variant to run, or ``None``
    for stock.  Kill-switch beats override beats backend eligibility;
    a variant that already fell back is never re-selected."""
    variants = FUSED_VARIANTS.get(op_name)
    if not variants or not fused_enabled():
        return None
    forced = _fused_override().get(op_name)
    if forced is not None:
        if forced == "stock":
            return None
        var = variants.get(forced)
        if var is None:
            raise MXNetError(
                "MXNET_TPU_OPS_FUSED_OVERRIDE names unknown variant "
                "%r of op %r (have %s)"
                % (forced, op_name, sorted(variants)))
        if (op_name, var.name) in _FUSED_FAILED:
            return None
        return var
    backend = _backend()
    for var in variants.values():
        if backend in var.backends \
                and (op_name, var.name) not in _FUSED_FAILED:
            return var
    return None


def _chaos_visit(op_name, variant, payload=None):
    """Visit the ``ops.fused`` chaos site for one variant dispatch
    (``name`` is ``op:variant`` so ``match`` can drill one kernel)."""
    from .. import chaos as _chaos

    return _chaos.visit("ops.fused", payload,
                        name="%s:%s" % (op_name, variant))


def _fallback_counter():
    if not _FALLBACK_FAMILY:
        from ..observability import metrics as _metrics

        with _FUSED_LOCK:
            if not _FALLBACK_FAMILY:
                _FALLBACK_FAMILY.append(_metrics.counter(
                    "ops_fused_fallback_total",
                    "fused-tier variants that raised at dispatch and "
                    "fell back to stock (one increment per (op, variant) "
                    "per process — the fast path silently degraded)",
                    ["op", "reason"]))
    return _FALLBACK_FAMILY[0]


def _record_fused_fallback(op_name, variant, exc):
    """Book a variant out of selection — once per (op, variant)."""
    reason = type(exc).__name__
    with _FUSED_LOCK:
        if (op_name, variant) in _FUSED_FAILED:
            return
        _FUSED_FAILED[(op_name, variant)] = reason
    _fallback_counter().labels(op_name, reason).inc()
    from ..observability.events import emit as _emit

    _emit("ops.fused.fallback", op=op_name, variant=variant,
          reason=reason, error=str(exc)[:200])


def dispatch_variant(op_name, stock_fn, *args, **kwargs):
    """The functional seam: run ``op_name``'s selected variant over
    plain arrays (``fn(*args, **kwargs)``), falling back to
    ``stock_fn`` with the same once-per-(op, variant) bookkeeping as
    :meth:`Op.apply`.  Constant-time when no variant is registered."""
    var = select_variant(op_name)
    if var is None:
        return stock_fn(*args, **kwargs)
    try:
        _chaos_visit(op_name, var.name)
        return var.fn(*args, **kwargs)
    except Exception as exc:  # noqa: BLE001 — fallback seam
        _record_fused_fallback(op_name, var.name, exc)
        return stock_fn(*args, **kwargs)


def fused_fallbacks():
    """Snapshot of booked fallbacks {(op, variant): reason class}."""
    with _FUSED_LOCK:
        return dict(_FUSED_FAILED)


def reset_fused_dispatch():
    """Test hook: clear the fallback book and cached backend/override so
    a re-configured environment re-selects from scratch."""
    with _FUSED_LOCK:
        _FUSED_FAILED.clear()
        del _FUSED_BACKEND[:]
        _OVERRIDE_CACHE[0] = None
        _OVERRIDE_CACHE[1] = {}
