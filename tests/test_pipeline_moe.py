"""Pipeline + expert parallelism tests on the 8-virtual-device CPU mesh
(the reference's multi-device-on-one-box test strategy, SURVEY.md §4 —
``test_multi_device_exec.py`` / ``test_model_parallel.py`` tier, extended to
the parallelism modes the reference lacks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mxnet_tpu.parallel import moe, pipeline


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _make_stages(rng, n_stages, d):
    out = []
    for i in range(n_stages):
        k1, k2 = jax.random.split(jax.random.fold_in(rng, i))
        out.append({"w": jax.random.normal(k1, (d, d)) * 0.5,
                    "b": jax.random.normal(k2, (d,)) * 0.1})
    return out


def _pipe_mesh(n=4):
    devs = jax.devices()[:n]
    if len(devs) < n:
        pytest.skip("need %d devices" % n)
    return Mesh(np.array(devs), ("pipe",))


def test_pipeline_matches_sequential():
    mesh = _pipe_mesh(4)
    rng = jax.random.PRNGKey(0)
    d, B = 6, 8
    stages = _make_stages(rng, 4, d)
    x = jax.random.normal(jax.random.fold_in(rng, 99), (B, d))

    want = x
    for p in stages:
        want = _stage_fn(p, want)

    stacked = pipeline.stack_stage_params(stages)
    got = pipeline.pipeline_apply(_stage_fn, stacked, x, mesh=mesh,
                                  n_microbatch=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_microbatch_counts():
    mesh = _pipe_mesh(4)
    rng = jax.random.PRNGKey(1)
    d, B = 4, 12
    stages = _make_stages(rng, 4, d)
    x = jax.random.normal(rng, (B, d))
    want = x
    for p in stages:
        want = _stage_fn(p, want)
    stacked = pipeline.stack_stage_params(stages)
    for n_mb in (2, 3, 6, 12):
        got = pipeline.pipeline_apply(_stage_fn, stacked, x, mesh=mesh,
                                      n_microbatch=n_mb)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    mesh = _pipe_mesh(4)
    rng = jax.random.PRNGKey(2)
    d, B = 4, 8
    stages = _make_stages(rng, 4, d)
    x = jax.random.normal(rng, (B, d))
    target = jax.random.normal(jax.random.fold_in(rng, 7), (B, d))
    stacked = pipeline.stack_stage_params(stages)

    def loss_pipe(p):
        y = pipeline.pipeline_apply(_stage_fn, p, x, mesh=mesh,
                                    n_microbatch=2)
        return jnp.mean((y - target) ** 2)

    def loss_seq(p):
        y = x
        for i in range(4):
            y = _stage_fn(jax.tree_util.tree_map(lambda a: a[i], p), y)
        return jnp.mean((y - target) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq[k]),
                                   rtol=1e-4, atol=1e-5)


def test_pipelined_trainer_learns():
    mesh = _pipe_mesh(4)
    rng = jax.random.PRNGKey(3)
    d, B = 4, 8
    stages = _make_stages(rng, 4, d)
    x = jax.random.normal(rng, (B, d))
    target = jnp.zeros((B, d))

    tr = pipeline.PipelinedTrainer(
        _stage_fn, lambda y, t: jnp.mean((y - t) ** 2), mesh,
        n_microbatch=2, learning_rate=0.2)
    params = tr.place_params(stages)
    step = tr.step_fn()
    losses = []
    for _ in range(10):
        l, params = step(params, x, target)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.9


def test_moe_routing_reference():
    # capacity ample → every token goes to its argmax expert, scaled by gate
    rng = jax.random.PRNGKey(0)
    d, h, E, B, S = 8, 16, 4, 2, 6
    params = moe.init_moe_params(rng, d, h, E)
    x = jax.random.normal(jax.random.fold_in(rng, 1), (B, S, d))
    out, aux = moe.moe_ffn(params, x, capacity_factor=float(E))
    tokens = np.asarray(x.reshape(B * S, d))
    logits = tokens @ np.asarray(params["router"])
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    want = np.zeros_like(tokens)
    for t in range(B * S):
        e = int(np.argmax(probs[t]))
        hdn = np.maximum(tokens[t] @ np.asarray(params["w1"][e]), 0)
        want[t] = probs[t, e] * (hdn @ np.asarray(params["w2"][e]))
    np.testing.assert_allclose(np.asarray(out).reshape(B * S, d), want,
                               rtol=1e-4, atol=1e-5)
    assert float(aux) > 0


def test_moe_capacity_drops_tokens():
    # capacity 1 per expert: at most E tokens survive routing
    rng = jax.random.PRNGKey(4)
    d, h, E, B, S = 4, 8, 2, 1, 8
    params = moe.init_moe_params(rng, d, h, E)
    x = jax.random.normal(rng, (B, S, d))
    out, _ = moe.moe_ffn(params, x, capacity_factor=2.0 / S)  # capacity=1
    nonzero_tokens = np.abs(np.asarray(out).reshape(B * S, d)).sum(-1) > 1e-9
    assert nonzero_tokens.sum() <= E


def test_moe_expert_parallel_matches_dense():
    devs = jax.devices()[:8]
    if len(devs) < 8:
        pytest.skip("need 8 devices")
    mesh = Mesh(np.array(devs).reshape(2, 4), ("data", "expert"))
    rng = jax.random.PRNGKey(5)
    d, h, E, B, S = 8, 16, 4, 4, 8
    params = moe.init_moe_params(rng, d, h, E)
    x = jax.random.normal(rng, (B, S, d))

    dense_out, dense_aux = moe.moe_ffn(params, x, capacity_factor=2.0)

    eshard = NamedSharding(mesh, P("expert"))
    sharded_params = {
        "router": jax.device_put(params["router"], NamedSharding(mesh, P())),
        "w1": jax.device_put(params["w1"], eshard),
        "w2": jax.device_put(params["w2"], eshard),
    }
    xs = jax.device_put(x, NamedSharding(mesh, P("data")))

    @jax.jit
    def run(p, xx):
        return moe.moe_ffn(p, xx, capacity_factor=2.0, mesh=mesh)

    with mesh:
        out, aux = run(sharded_params, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense_out),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(dense_aux), rtol=1e-5)


def test_moe_differentiable():
    rng = jax.random.PRNGKey(6)
    d, h, E, B, S = 4, 8, 2, 2, 4
    params = moe.init_moe_params(rng, d, h, E)
    x = jax.random.normal(rng, (B, S, d))

    def loss(p):
        out, aux = moe.moe_ffn(p, x)
        return jnp.mean(out ** 2) + 0.01 * aux

    grads = jax.grad(loss)(params)
    for k, g in grads.items():
        assert np.isfinite(np.asarray(g)).all(), k
    assert np.abs(np.asarray(grads["router"])).sum() > 0


def test_remat_matches_nonremat():
    # memonger analog: jax.checkpoint remat must not change numerics
    import mxnet_tpu as mx
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    devs = jax.devices()[:2]
    mesh = Mesh(np.array(devs), ("data",))
    sym = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Activation(mx.sym.FullyConnected(
            mx.sym.Variable("data"), num_hidden=16, name="fc1"),
            act_type="relu"), num_hidden=4, name="fc2"), name="softmax")
    batch_np = {
        "data": np.random.RandomState(0).randn(4, 8).astype(np.float32),
        "softmax_label": np.array([0, 1, 2, 3], np.float32)}
    results = {}
    for remat in (False, True):
        tr = ShardedTrainer(sym, mesh, data_shapes={"data": (4, 8)},
                            label_shapes={"softmax_label": (4,)},
                            momentum=0.9, remat=remat,
                            remat_policy="dots_saveable" if remat else None)
        params, moms, aux = tr.init(seed=0)
        batch = tr.place_batch(batch_np)
        step = tr.step_fn()
        for i in range(3):
            outs, params, moms, aux = step(params, moms, aux, batch,
                                           jax.random.PRNGKey(i))
        results[remat] = {k: np.asarray(v) for k, v in params.items()}
    for k in results[False]:
        np.testing.assert_allclose(results[True][k], results[False][k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_moe_symbol_op_sharded():
    # MoELayer as a graph node: trains under a data x expert mesh with
    # expert-sharded weights; matches the functional moe_ffn numerics
    import mxnet_tpu as mx
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    devs = jax.devices()[:4]
    if len(devs) < 4:
        pytest.skip("need 4 devices")
    data = mx.sym.Variable("data")
    moe_out = mx.sym.MoELayer(data, num_experts=4, hidden_size=32,
                              name="moe")
    tokens = mx.sym.Reshape(moe_out[0], shape=(-1, 16))
    logits = mx.sym.FullyConnected(tokens, num_hidden=8, name="out")
    label = mx.sym.Reshape(mx.sym.Variable("softmax_label"), shape=(-1,))
    net = mx.sym.Group(
        [mx.sym.SoftmaxOutput(logits, label, name="softmax"),
         mx.sym.MakeLoss(moe_out[1] * 0.01, name="auxl")])
    mesh = Mesh(np.array(devs).reshape(2, 2), ("data", "expert"))
    B, S = 4, 8
    tr = ShardedTrainer(
        net, mesh, data_shapes={"data": (B, S, 16)},
        label_shapes={"softmax_label": (B, S)}, momentum=0.9,
        param_specs={"moe_w1_weight": P("expert"),
                     "moe_w2_weight": P("expert")})
    params, moms, aux = tr.init(seed=0)
    batch = tr.place_batch({
        "data": np.random.RandomState(0).randn(B, S, 16).astype(np.float32),
        "softmax_label": np.random.RandomState(1).randint(
            0, 8, (B, S)).astype(np.float32)})
    step = tr.step_fn()
    for i in range(3):
        outs, params, moms, aux = step(params, moms, aux, batch,
                                       jax.random.PRNGKey(i))
    assert params["moe_w1_weight"].sharding.spec == P("expert")
    assert np.isfinite(float(np.asarray(outs[1])[0]))

    # eager single-device forward matches the functional path
    x = np.random.RandomState(2).randn(2, 4, 16).astype(np.float32)
    gw = np.asarray(params["moe_gate_weight"])
    w1 = np.asarray(params["moe_w1_weight"])
    w2 = np.asarray(params["moe_w2_weight"])
    out_op = mx.nd.MoELayer(mx.nd.array(x), mx.nd.array(gw),
                            mx.nd.array(w1), mx.nd.array(w2),
                            num_experts=4, hidden_size=32)
    fn_out, _ = moe.moe_ffn(
        {"router": jnp.asarray(gw), "w1": jnp.asarray(w1),
         "w2": jnp.asarray(w2)}, jnp.asarray(x))
    np.testing.assert_allclose(out_op[0].asnumpy(), np.asarray(fn_out),
                               rtol=1e-4, atol=1e-5)
