"""Shared training harness (behavioral parity: reference
``example/image-classification/common/fit.py:45-89`` — same CLI surface with
``--tpus`` in place of ``--gpus``, kvstore creation, lr schedule from epoch
steps, checkpointing, top-k metrics, Speedometer logging)."""

import argparse
import logging
import os
import time

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))
import mxnet_tpu as mx


def _get_lr_scheduler(args, kv):
    if not args.lr_factor or args.lr_factor >= 1:
        return (args.lr, None)
    epoch_size = _get_epoch_size(args, kv)
    begin_epoch = args.load_epoch if args.load_epoch else 0
    step_epochs = [int(l) for l in args.lr_step_epochs.split(",")]
    lr = args.lr
    for s in step_epochs:
        if begin_epoch >= s:
            lr *= args.lr_factor
    if lr != args.lr:
        logging.info("Adjust learning rate to %e for epoch %d", lr, begin_epoch)
    steps = [
        epoch_size * (x - begin_epoch)
        for x in step_epochs
        if x - begin_epoch > 0
    ]
    return (lr, mx.lr_scheduler.MultiFactorScheduler(step=steps,
                                                     factor=args.lr_factor))


def _get_epoch_size(args, kv):
    return int(args.num_examples / args.batch_size / kv.num_workers)


def _load_model(args, rank=0):
    if args.load_epoch is None or args.model_prefix is None:
        return (None, None, None)
    model_prefix = args.model_prefix
    if rank > 0 and os.path.exists("%s-%d-symbol.json" % (model_prefix, rank)):
        model_prefix += "-%d" % rank
    sym, arg_params, aux_params = mx.model.load_checkpoint(
        model_prefix, args.load_epoch)
    logging.info("Loaded model %s_%04d.params", model_prefix, args.load_epoch)
    return (sym, arg_params, aux_params)


def _save_model(args, rank=0):
    if args.model_prefix is None:
        return None
    dst_dir = os.path.dirname(args.model_prefix)
    if dst_dir and not os.path.isdir(dst_dir):
        os.makedirs(dst_dir, exist_ok=True)
    return mx.callback.do_checkpoint(
        args.model_prefix if rank == 0 else "%s-%d" % (args.model_prefix, rank))


def add_fit_args(parser):
    """Add training CLI args (reference ``fit.py:add_fit_args`` surface)."""
    train = parser.add_argument_group("Training", "model training")
    train.add_argument("--network", type=str, help="the neural network to use")
    train.add_argument("--num-layers", type=int,
                       help="number of layers in the neural network")
    train.add_argument("--tpus", type=str, default=None,
                       help="list of tpus to run, e.g. 0 or 0,2,5. empty means"
                            " using first device (cpu fallback off-tpu)")
    train.add_argument("--kv-store", type=str, default="device",
                       help="key-value store type")
    train.add_argument("--num-epochs", type=int, default=100,
                       help="max num of epochs")
    train.add_argument("--lr", type=float, default=0.1, help="initial lr")
    train.add_argument("--lr-factor", type=float, default=0.1,
                       help="the ratio to reduce lr on each step")
    train.add_argument("--lr-step-epochs", type=str, default="30,60",
                       help="the epochs to reduce the lr, e.g. 30,60")
    train.add_argument("--optimizer", type=str, default="sgd", help="optimizer")
    train.add_argument("--mom", type=float, default=0.9, help="momentum")
    train.add_argument("--wd", type=float, default=0.0001, help="weight decay")
    train.add_argument("--batch-size", type=int, default=128, help="batch size")
    train.add_argument("--disp-batches", type=int, default=20,
                       help="show progress for every n batches")
    train.add_argument("--model-prefix", type=str,
                       help="model prefix for checkpoints")
    train.add_argument("--monitor", dest="monitor", type=int, default=0,
                       help="log network parameters every N iters if larger than 0")
    train.add_argument("--load-epoch", type=int,
                       help="load the model on an epoch using the model-prefix")
    train.add_argument("--top-k", type=int, default=0,
                       help="report the top-k accuracy. 0 means no report")
    train.add_argument("--test-io", type=int, default=0,
                       help="1 means test reading speed without training")
    train.add_argument("--dtype", type=str, default="float32",
                       help="float32 or bfloat16")
    return train


def get_devices(args):
    """``--tpus`` -> context list (the reference's ``--gpus`` mapping)."""
    return mx.context.devices_from_arg(args.tpus)


def fit(args, network, data_loader, **kwargs):
    """Train ``network`` on data from ``data_loader(args, kv)``."""
    kv = mx.kvstore.create(args.kv_store)

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s Node[" + str(kv.rank) + "] %(message)s")
    logging.info("start with arguments %s", args)

    (train, val) = data_loader(args, kv)
    if args.test_io:
        tic = time.time()
        for i, batch in enumerate(train):
            for j in batch.data:
                j.wait_to_read()
            if (i + 1) % args.disp_batches == 0:
                logging.info("Batch [%d]\tSpeed: %.2f samples/sec", i,
                             args.disp_batches * args.batch_size / (time.time() - tic))
                tic = time.time()
        return

    sym, arg_params, aux_params = _load_model(args, kv.rank)
    if sym is not None:
        assert sym.tojson() == network.tojson()

    devs = get_devices(args)
    lr, lr_scheduler = _get_lr_scheduler(args, kv)

    model = mx.mod.Module(context=devs, symbol=network)

    optimizer_params = {
        "learning_rate": lr,
        "wd": args.wd,
        "lr_scheduler": lr_scheduler,
    }
    if args.optimizer in ("sgd", "nag", "dcasgd", "ccsgd", "sgld"):
        optimizer_params["momentum"] = args.mom

    monitor = mx.mon.Monitor(args.monitor, pattern=".*") if args.monitor > 0 else None

    initializer = mx.initializer.Xavier(rnd_type="gaussian",
                                        factor_type="in", magnitude=2)

    eval_metrics = ["accuracy"]
    if args.top_k > 0:
        eval_metrics.append(mx.metric.create("top_k_accuracy", top_k=args.top_k))

    batch_end_callbacks = [mx.callback.Speedometer(args.batch_size,
                                                   args.disp_batches)]
    if "batch_end_callback" in kwargs:
        cbs = kwargs.pop("batch_end_callback")
        batch_end_callbacks += cbs if isinstance(cbs, list) else [cbs]

    model.fit(
        train,
        begin_epoch=args.load_epoch if args.load_epoch else 0,
        num_epoch=args.num_epochs,
        eval_data=val,
        eval_metric=eval_metrics,
        kvstore=kv,
        optimizer=args.optimizer,
        optimizer_params=optimizer_params,
        initializer=initializer,
        arg_params=arg_params,
        aux_params=aux_params,
        batch_end_callback=batch_end_callbacks,
        epoch_end_callback=_save_model(args, kv.rank),
        allow_missing=True,
        monitor=monitor,
        **kwargs,
    )
    return model
