"""``make efficiency``: run a short instrumented fit and print the
compute-efficiency books — per-cache HLO cost analysis (FLOPs, bytes,
arithmetic intensity, memory footprint), the model-FLOPs/MFU summary,
and the goodput ledger.

Drives the efficiency accounting plane end to end on the CPU backend: a
pipelined ``ShardedTrainer.fit`` records compile cost analysis for
every jit cache (``trainer_compile_flops{cache}``), derives
``trainer_step_model_flops`` / ``model_flops_utilization`` from the
compiled program, and closes a goodput ledger over the fit wall.  Exits
non-zero if no compile FLOPs were accounted, no train-step model-FLOPs
figure was derived, or the goodput books fail the 5% reconciliation
gate (productive + every badput cause must match
``fit_wall_seconds_total`` — the same falsifiability contract tier-1
enforces).

Run:  python tools/efficiency_report.py
"""

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_TPU_METRICS", "1")


def main():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    import mxnet_tpu as mx
    from mxnet_tpu import observability as obs
    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(net, num_hidden=8, name="fc2"),
        name="softmax")
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    tr = ShardedTrainer(net, mesh, data_shapes={"data": (8, 6)},
                        label_shapes={"softmax_label": (8,)},
                        momentum=0.9, rescale_grad=1.0 / 8,
                        pipeline_steps=2)
    rs = np.random.RandomState(0)
    # 10 optimizer steps: 5 full flushes of 2
    it = NDArrayIter(rs.randn(80, 6).astype(np.float32),
                     rs.randint(0, 8, (80,)).astype(np.float32),
                     batch_size=8)
    tr.fit(it, num_epoch=1, seed=0)

    print("HLO cost accounting (per jit cache):")
    print(obs.format_efficiency())
    print()
    print("goodput ledger:")
    print(obs.format_goodput())

    rows, _ = obs.efficiency_table()
    if not rows:
        print("FAIL: no compile cost analysis was accounted",
              file=sys.stderr)
        return 1
    if obs.model_flops_per_step() is None:
        print("FAIL: no train-step model-FLOPs figure was derived",
              file=sys.stderr)
        return 1

    ok, wall, accounted = obs.goodput_reconciles(tol=0.05)
    drift = abs(accounted - wall) / wall if wall else 1.0
    print("goodput books vs fit wall: %.2f%% drift" % (100 * drift))
    if not ok:
        print("FAIL: goodput books off by more than 5%% "
              "(wall=%.4fs accounted=%.4fs)" % (wall, accounted),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
