"""Testing utilities (parity: reference ``python/mxnet/test_utils.py``):
numeric-gradient checking, golden forward/backward checks, cross-context
consistency — the reference's whole test strategy (SURVEY.md §4), with JAX
autodiff as the oracle alongside finite differences.
"""

from __future__ import annotations

import numpy as _np

from . import ndarray as nd
from . import symbol as sym
from .context import Context, cpu, current_context
from .ndarray import NDArray, array

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "same", "rand_ndarray", "random_arrays",
           "numeric_grad", "check_numeric_gradient", "check_symbolic_forward",
           "check_symbolic_backward", "check_consistency", "simple_forward",
           "get_rtol", "get_atol", "find_max_violation",
           "almost_equal_ignore_nan", "assert_almost_equal_ignore_nan",
           "np_reduce", "retry", "list_gpus", "set_env_var", "download",
           "check_speed"]

_DEFAULT_CTX = [None]


def default_context():
    """(parity: ``test_utils.py:default_context``)"""
    if _DEFAULT_CTX[0] is not None:
        return _DEFAULT_CTX[0]
    return current_context()


def set_default_context(ctx):
    _DEFAULT_CTX[0] = ctx


def default_dtype():
    return _np.float32


def same(a, b):
    return _np.array_equal(a, b)


def almost_equal(a, b, rtol=None, atol=None):
    rtol = rtol or 1e-5
    atol = atol or 1e-20
    return _np.allclose(a, b, rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    rtol = rtol or 1e-5
    atol = atol or 1e-20
    if almost_equal(a, b, rtol, atol):
        return
    err = _np.max(_np.abs(_np.asarray(a) - _np.asarray(b)))
    raise AssertionError(
        "Items %s and %s are not almost equal (max abs err %g, rtol=%g, atol=%g)"
        % (names[0], names[1], err, rtol, atol))


def rand_ndarray(shape, ctx=None, dtype=_np.float32):
    return array(_np.random.uniform(-1.0, 1.0, shape).astype(dtype),
                 ctx=ctx or default_context())


def random_arrays(*shapes):
    arrays = [_np.random.randn(*s).astype(_np.float32) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def _parse_location(symbol, location, ctx):
    assert isinstance(location, (dict, list, tuple))
    if isinstance(location, dict):
        if set(location.keys()) != set(symbol.list_arguments()):
            raise ValueError(
                "Symbol arguments and keys of the given location do not match."
                "symbol args:%s, location.keys():%s"
                % (str(set(symbol.list_arguments())), str(set(location.keys()))))
    else:
        location = {k: v for k, v in zip(symbol.list_arguments(), location)}
    location = {
        k: array(v, ctx=ctx) if isinstance(v, _np.ndarray) else v
        for k, v in location.items()
    }
    return location


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Finite-difference gradients (parity: ``test_utils.py:numeric_grad``)."""
    approx_grads = {k: _np.zeros(v.shape, dtype=_np.float32)
                    for k, v in location.items()}
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    for k in location:
        old_value = location[k].copy()
        for i in range(int(_np.prod(old_value.shape))):
            # inplace update
            loc = _np.unravel_index(i, old_value.shape)
            old_v = old_value[loc]
            perturbed = old_value.copy()
            perturbed[loc] = old_v + eps / 2
            executor.arg_dict[k][:] = perturbed
            if aux_states is not None:
                for key, val in aux_states.items():
                    executor.aux_dict[key][:] = val
            executor.forward(is_train=use_forward_train)
            f_peps = executor.outputs[0].asnumpy().sum()
            perturbed[loc] = old_v - eps / 2
            executor.arg_dict[k][:] = perturbed
            if aux_states is not None:
                for key, val in aux_states.items():
                    executor.aux_dict[key][:] = val
            executor.forward(is_train=use_forward_train)
            f_neps = executor.outputs[0].asnumpy().sum()
            approx_grads[k].ravel()[i] = (f_peps - f_neps) / eps
        executor.arg_dict[k][:] = old_value
    return approx_grads


def check_numeric_gradient(sym_, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None):
    """Verify symbolic gradients vs finite differences (parity:
    ``test_utils.py:check_numeric_gradient:360``)."""
    ctx = ctx or default_context()
    location = _parse_location(sym_, location, ctx)
    location_npy = {k: v.asnumpy() for k, v in location.items()}
    if aux_states is not None:
        aux_npy = {k: _np.asarray(v) for k, v in aux_states.items()}
    else:
        aux_npy = None

    if grad_nodes is None:
        grad_nodes = sym_.list_arguments()
        grad_req = {k: "write" for k in grad_nodes}
    elif isinstance(grad_nodes, (list, tuple)):
        grad_nodes = list(grad_nodes)
        grad_req = {k: "write" if k in grad_nodes else "null"
                    for k in sym_.list_arguments()}
    elif isinstance(grad_nodes, dict):
        grad_req = grad_nodes.copy()
        grad_nodes = grad_nodes.keys()
    else:
        raise ValueError

    input_shape = {k: v.shape for k, v in location.items()}
    _, out_shapes, _ = sym_.infer_shape(**input_shape)
    # project the output with random weights so every output element's gradient
    # is exercised (same trick as the reference's check_numeric_gradient)
    proj = sym.Variable("__random_proj")
    out = sym.MakeLoss(sym.sum(sym_ * proj))

    location = dict(location)
    location["__random_proj"] = array(
        _np.random.uniform(-1.0, 1.0, out_shapes[0]).astype("float32"), ctx)
    args_grad_npy = {k: _np.random.normal(0, 0.01, size=location[k].shape)
                     for k in grad_nodes}
    args_grad = {k: array(v, ctx) for k, v in args_grad_npy.items()}

    executor = out.bind(ctx, args=location, args_grad=args_grad,
                        grad_req=grad_req,
                        aux_states={k: array(v, ctx) for k, v in aux_npy.items()}
                        if aux_npy else None)

    executor.forward(is_train=True)
    executor.backward()
    symbolic_grads = {k: executor.grad_dict[k].asnumpy() for k in grad_nodes}

    numeric_gradients = numeric_grad(
        executor, {k: v for k, v in location_npy.items()},
        aux_npy, eps=numeric_eps, use_forward_train=use_forward_train)

    for name in grad_nodes:
        fd_grad = numeric_gradients[name]
        sym_grad = symbolic_grads[name]
        if grad_req[name] == "write":
            assert_almost_equal(fd_grad, sym_grad, rtol, atol or 1e-4,
                                ("NUMERICAL_%s" % name, "BACKWARD_%s" % name))
        elif grad_req[name] == "add":
            assert_almost_equal(fd_grad, sym_grad - args_grad_npy[name], rtol,
                                atol or 1e-4,
                                ("NUMERICAL_%s" % name, "BACKWARD_%s" % name))


def check_symbolic_forward(sym_, location, expected, rtol=1e-5, atol=None,
                           aux_states=None, ctx=None):
    """Compare forward outputs against golden values (parity:
    ``test_utils.py:check_symbolic_forward:473``)."""
    ctx = ctx or default_context()
    location = _parse_location(sym_, location, ctx)
    aux = {k: array(_np.asarray(v), ctx) for k, v in (aux_states or {}).items()} \
        if aux_states else None
    executor = sym_.bind(ctx, args=location, aux_states=aux)
    outputs = executor.forward()
    for output, expect in zip(outputs, expected):
        assert_almost_equal(output.asnumpy(), expect, rtol, atol or 1e-20)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym_, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None):
    """Compare backward grads against golden values (parity:
    ``test_utils.py:check_symbolic_backward:526``)."""
    ctx = ctx or default_context()
    location = _parse_location(sym_, location, ctx)
    if isinstance(expected, (list, tuple)):
        expected = {k: v for k, v in zip(sym_.list_arguments(), expected)}
    args_grad_npy = {k: _np.random.normal(size=v.shape)
                     for k, v in expected.items()}
    args_grad_data = {k: array(v, ctx) for k, v in args_grad_npy.items()}
    if isinstance(grad_req, str):
        grad_req = {k: grad_req for k in sym_.list_arguments()}
    elif isinstance(grad_req, (list, tuple)):
        grad_req = {k: v for k, v in zip(sym_.list_arguments(), grad_req)}
    aux = {k: array(_np.asarray(v), ctx) for k, v in (aux_states or {}).items()} \
        if aux_states else None
    executor = sym_.bind(ctx, args=location, args_grad=args_grad_data,
                         grad_req=grad_req, aux_states=aux)
    executor.forward(is_train=True)
    if isinstance(out_grads, (tuple, list)):
        out_grads = [array(_np.asarray(v), ctx) for v in out_grads]
    elif isinstance(out_grads, dict):
        out_grads = [array(_np.asarray(v), ctx) for v in out_grads.values()]
    executor.backward(out_grads)
    grads = {k: v.asnumpy() for k, v in executor.grad_dict.items() if v is not None}
    for name in expected:
        if grad_req[name] == "write":
            assert_almost_equal(grads[name], expected[name], rtol, atol or 1e-20,
                                ("EXPECTED_%s" % name, "BACKWARD_%s" % name))
        elif grad_req[name] == "add":
            assert_almost_equal(grads[name], args_grad_npy[name] + expected[name],
                                rtol, atol or 1e-20,
                                ("EXPECTED_%s" % name, "BACKWARD_%s" % name))
    return grads


def check_consistency(sym_, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, aux_params=None, tol=None,
                      raise_on_err=True):
    """Run the same graph on several contexts and cross-check outputs/grads
    (parity: ``test_utils.py:check_consistency:676``; cpu-vs-tpu here)."""
    tol = tol or 1e-3
    assert len(ctx_list) > 1
    if isinstance(sym_, sym.Symbol):
        sym_list = [sym_] * len(ctx_list)
    else:
        sym_list = sym_
    results = []
    data_vals = {}  # one draw shared by every context (incl. data inputs)
    for s, ctx_spec in zip(sym_list, ctx_list):
        ctx_spec = dict(ctx_spec)
        ctx = ctx_spec.pop("ctx", None) or cpu()
        type_dict = ctx_spec.pop("type_dict", {})
        shapes = ctx_spec
        exe = s.simple_bind(ctx, grad_req=grad_req, type_dict=type_dict, **shapes)
        # a PARTIAL arg_params (e.g. only integer-valued inputs pinned) is
        # completed with shared random draws — a param left at the bind's
        # zeros would make the cross-check degenerate
        arg_params = {} if arg_params is None else dict(arg_params)
        for name, arr in exe.arg_dict.items():
            if name not in shapes and name not in arg_params:
                arg_params[name] = _np.random.normal(
                    size=arr.shape, scale=scale).astype(_np.float32)
        for name, arr in exe.arg_dict.items():
            if name in shapes:
                if name in arg_params:
                    arr[:] = arg_params[name]
                else:
                    if name not in data_vals:
                        data_vals[name] = _np.random.uniform(
                            -1, 1, arr.shape).astype(_np.float32)
                    arr[:] = data_vals[name]
            elif name in arg_params:
                arr[:] = arg_params[name]
        if aux_params:
            for name, arr in exe.aux_dict.items():
                if name in aux_params:
                    arr[:] = aux_params[name]
        exe.forward(is_train=(grad_req != "null"))
        if grad_req != "null":
            exe.backward()
        results.append(exe)
    out0 = [o.asnumpy() for o in results[0].outputs]
    for exe in results[1:]:
        for a, b in zip(out0, exe.outputs):
            assert_almost_equal(a, b.asnumpy(), rtol=tol, atol=tol)
    if grad_req != "null":
        # gradients must agree too (reference compares exe.grad_arrays)
        grads0 = {n: g.asnumpy()
                  for n, g in results[0].grad_dict.items() if g is not None}
        for exe in results[1:]:
            for n, g0 in grads0.items():
                g = exe.grad_dict.get(n)
                if g is not None:
                    assert_almost_equal(g0, g.asnumpy(), rtol=tol, atol=tol,
                                        names=("grad:%s" % n,) * 2)
    return results


def simple_forward(sym_, ctx=None, is_train=False, **inputs):
    """Bind + forward in one call, returning numpy (parity:
    ``test_utils.py:simple_forward``)."""
    ctx = ctx or default_context()
    inputs = {k: array(v, ctx) for k, v in inputs.items()}
    exe = sym_.bind(ctx, args=inputs)
    exe.forward(is_train=is_train)
    outputs = [o.asnumpy() for o in exe.outputs]
    if len(outputs) == 1:
        outputs = outputs[0]
    return outputs


def get_rtol(rtol=None):
    """Default relative threshold for regression checks (parity:
    ``test_utils.py:get_rtol``)."""
    return 1e-5 if rtol is None else rtol


def get_atol(atol=None):
    """Default absolute threshold (parity: ``test_utils.py:get_atol``)."""
    return 1e-20 if atol is None else atol


def find_max_violation(a, b, rtol=None, atol=None):
    """Index and magnitude of the worst tolerance violation (parity:
    ``test_utils.py:find_max_violation``)."""
    rtol, atol = get_rtol(rtol), get_atol(atol)
    diff = _np.abs(a - b)
    tol = atol + rtol * _np.abs(b)
    violation = diff / (tol + 1e-20)
    idx = _np.unravel_index(_np.argmax(violation), violation.shape)
    return idx, float(_np.max(violation))


def almost_equal_ignore_nan(a, b, rtol=None, atol=None):
    """Almost-equal with NaNs masked out of BOTH arrays (parity:
    ``test_utils.py:almost_equal_ignore_nan``)."""
    a, b = _np.copy(a), _np.copy(b)
    mask = _np.logical_or(_np.isnan(a), _np.isnan(b))
    a[mask] = 0
    b[mask] = 0
    return almost_equal(a, b, rtol, atol)


def assert_almost_equal_ignore_nan(a, b, rtol=None, atol=None,
                                   names=("a", "b")):
    """Assert variant of :func:`almost_equal_ignore_nan`."""
    a, b = _np.copy(a), _np.copy(b)
    mask = _np.logical_or(_np.isnan(a), _np.isnan(b))
    a[mask] = 0
    b[mask] = 0
    assert_almost_equal(a, b, rtol, atol, names)


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """Reduce with list-axis + keepdims compatibility (parity:
    ``test_utils.py:np_reduce``)."""
    if isinstance(axis, int):
        axis = [axis]
    axes = list(range(dat.ndim)) if axis is None else list(axis)
    ret = dat
    for i, ax in enumerate(sorted(axes)):
        ret = numpy_reduce_func(ret, axis=ax - i)
    if keepdims:
        shape = list(dat.shape)
        for ax in axes:
            shape[ax] = 1
        ret = ret.reshape(tuple(shape))
    return ret


def retry(n):
    """Decorator retrying a stochastic test up to ``n`` times (parity:
    ``test_utils.py:retry``)."""
    assert n > 0

    def decorate(f):
        def wrapper(*args, **kwargs):
            err = None
            for _ in range(n):
                try:
                    f(*args, **kwargs)
                    return
                except AssertionError as e:
                    err = e
            raise err

        return wrapper

    return decorate


def list_gpus():
    """Accelerator device indices (parity: ``test_utils.py:list_gpus`` —
    here the TPU/accelerator chips visible to jax)."""
    import jax

    try:
        return [d.id for d in jax.devices() if d.platform != "cpu"]
    except RuntimeError:
        return []


def set_env_var(key, val, default_val=""):
    """Set an env var, returning the previous value (parity:
    ``test_utils.py:set_env_var``)."""
    import os

    prev = os.environ.get(key, default_val)
    os.environ[key] = str(val)
    return prev


def download(url, fname=None, dirname=None, overwrite=False):
    """Download ``url`` to a local file and return its path (role parity:
    ``test_utils.py:833``).  Two deliberate divergences from the reference:
    the guessed filename strips any query string (the reference would name a
    file ``f.bin?x=1``), and the skip-if-exists check runs *after* the
    dirname join, so ``download(url, dirname='dl')`` skips on ``dl/f.bin``
    rather than on a stray ``./f.bin``.  Works against any HTTP server; the
    test exercises it against a localhost server because this environment
    has no egress.
    """
    import logging
    import os

    if fname is None:
        fname = url.split("/")[-1].split("?")[0] or "index.html"
    if dirname is None:
        dirname = os.path.dirname(fname)
    else:
        fname = os.path.join(dirname, fname)
    if not overwrite and os.path.exists(fname):
        logging.info("%s exists, skipping download", fname)
        return fname
    if dirname != "" and not os.path.exists(dirname):
        os.makedirs(dirname, exist_ok=True)

    import urllib.request

    with urllib.request.urlopen(url) as r:
        status = getattr(r, "status", 200)
        if not 200 <= status < 300:
            raise IOError("failed to open %s (HTTP %s)" % (url, status))
        tmp = fname + ".part"
        try:
            with open(tmp, "wb") as f:
                while True:
                    chunk = r.read(1 << 16)
                    if not chunk:
                        break
                    f.write(chunk)
            os.replace(tmp, fname)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
    logging.info("downloaded %s into %s successfully", url, fname)
    return fname


def check_speed(sym, location=None, ctx=None, N=20, grad_req=None,
                typ="whole", **kwargs):
    """Average seconds per forward(+backward) run of ``sym`` (parity:
    ``test_utils.py:check_speed``)."""
    import time

    ctx = ctx or default_context()
    if grad_req is None:
        grad_req = "write"
    if location is None:
        exe = sym.simple_bind(ctx, grad_req=grad_req, **kwargs)
        location = {k: _np.random.normal(size=arr.shape, scale=1.0)
                    for k, arr in exe.arg_dict.items()}
    else:
        exe = sym.simple_bind(ctx, grad_req=grad_req,
                              **{k: v.shape for k, v in location.items()})
    for name, value in location.items():
        exe.arg_dict[name][:] = value

    if typ == "whole":
        def run():
            exe.forward(is_train=True)
            exe.backward()
            return exe.grad_arrays
    elif typ == "forward":
        def run():
            exe.forward(is_train=False)
            return exe.outputs
    else:
        raise ValueError("typ can only be 'whole' or 'forward'")

    import jax

    jax.block_until_ready([o._data for o in run() if o is not None])  # warm
    tic = time.time()
    out = None
    for _ in range(N):
        out = run()
    jax.block_until_ready([o._data for o in out if o is not None])
    return (time.time() - tic) / N
