"""Elastic resize worker script: ``launch.py -n 2 -s 2 --elastic-spares 2``
runs 2 live parameter-server shards plus 2 blank spares parked with the
cluster secret (addresses in ``MXNET_TPU_ELASTIC_SPARE_ADDRS``).

Mid-training, rank 0 grows the PS plane 2→4 through ``kv.resize()`` —
a live two-phase cutover onto the pre-warmed spares — keeps pushing at
the new striping, then shrinks back 4→2.  Rank 1 never calls resize:
its pushes to a key's old home are fenced by ``StaleEpochError`` with
the sealed tombstone forwarding the new shard list, and its group
re-routes without coordination.  Asserts:
* both resizes commit (epoch 1 then 2) with no lost/duplicated update,
* every worker converges exactly as a fixed-topology run would,
* striped big-array chunks follow the shard count across both cutovers.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import mxnet_tpu as mx
from mxnet_tpu.parallel import init_process_group


def main():
    addrs_env = os.environ.get("MXNET_TPU_ASYNC_PS_ADDRS")
    spares_env = os.environ.get("MXNET_TPU_ELASTIC_SPARE_ADDRS")
    assert addrs_env, "launcher must provide server addresses (-s N)"
    assert spares_env, "launcher must park spares (--elastic-spares K)"
    live = addrs_env.split(",")
    spares = spares_env.split(",")
    assert len(live) == 2 and len(spares) == 2, (live, spares)
    init_process_group()
    kv = mx.kv.create("dist_async")
    rank = kv.rank
    group = kv._async
    assert group.num_servers == 2, group.num_servers

    # force a tiny stripe bound so 'big' stripes across every shard
    group._bound = 64
    shape_small, shape_big = (3, 4), (16, 16)
    target = 3.0
    kv.init("alpha", mx.nd.ones(shape_small))
    kv.init("big", mx.nd.ones(shape_big))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05,
                                      rescale_grad=1.0, wd=0.0))
    kv.barrier()                     # both workers seeded before resizing

    for step in range(30):
        if rank == 0 and step == 5:
            # grow onto the parked spares, live, mid-training: rank 1
            # discovers the new striping through tombstone forwarding
            r = kv.resize(live + spares)
            assert r["epoch"] == 1, r
        if rank == 0 and step == 20:
            r = kv.resize(live)      # and drain back down
            assert r["epoch"] == 2, r
        for key, shape in (("alpha", shape_small), ("big", shape_big)):
            w = mx.nd.zeros(shape)
            kv.pull(key, out=w)
            kv.push(key, mx.nd.array(w.asnumpy() - target))

    kv.barrier()
    if rank == 0:
        assert group.topology_epoch == 2, group.topology_epoch
        assert len(group._specs) == 2, group._specs

    for key, shape in (("alpha", shape_small), ("big", shape_big)):
        w = mx.nd.zeros(shape)
        kv.pull(key, out=w)
        err = float(np.abs(w.asnumpy() - target).max())
        assert err < 0.5, (key, err)

    sys.stdout.write("worker %d: elastic resize OK\n" % rank)
    sys.stdout.flush()


if __name__ == "__main__":
    main()
