"""Machine audit of the Python frontend surface vs the reference package.

Parses every module of the reference's ``python/mxnet`` with ``ast`` (the
reference package is not importable here — it needs libmxnet.so) and
checks that each public class/function/alias resolves in ``mxnet_tpu``'s
corresponding namespace.  Complements ``tools/op_audit.py`` (which audits
the operator registry): together they make COVERAGE.md's parity claims
machine-checkable.

Exit 0 iff every reference name is present or explicitly accounted for.
Run:  python tools/frontend_audit.py [--ref PATH] [-v]
"""

import argparse
import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# reference module (under python/mxnet/) -> our attribute path from the
# package root; None = skip with the reason in SKIPPED_MODULES
MODULE_MAP = {
    "ndarray.py": "ndarray",
    "symbol.py": "symbol",
    "executor.py": "executor",
    "io.py": "io",
    "kvstore.py": "kvstore",
    "kvstore_server.py": "kvstore_server",
    "optimizer.py": "optimizer",
    "initializer.py": "initializer",
    "metric.py": "metric",
    "lr_scheduler.py": "lr_scheduler",
    "callback.py": "callback",
    "model.py": "model",
    "monitor.py": "monitor",
    "image.py": "image",
    "recordio.py": "recordio",
    "operator.py": "operator",
    "random.py": "random",
    "context.py": "context",
    "attribute.py": "attribute",
    "name.py": "name",
    "profiler.py": "profiler",
    "visualization.py": "visualization",
    "rtc.py": "rtc",
    "test_utils.py": "test_utils",
    "executor_manager.py": "executor_manager",
    "module/module.py": "module.module",
    "module/base_module.py": "module.base_module",
    "module/bucketing_module.py": "module.bucketing_module",
    "module/sequential_module.py": "module.sequential_module",
    "module/python_module.py": "module.python_module",
    "module/executor_group.py": "module.executor_group",
    "rnn/rnn_cell.py": "rnn.rnn_cell",
    "rnn/io.py": "rnn.io",
    "rnn/rnn.py": "rnn.rnn",
    "contrib/autograd.py": "contrib.autograd",
    "contrib/tensorboard.py": "contrib.tensorboard",
}

SKIPPED_MODULES = {
    "base.py": "ctypes bridge internals (our base.py has its own surface)",
    "libinfo.py": "shared-library discovery — no .so lookup needed",
    "ndarray_doc.py": "doc-generation helper for the C registry",
    "symbol_doc.py": "doc-generation helper for the C registry",
    "torch.py": "torch bridge is torch_bridge.py (different backend API)",
    "misc.py": "deprecated empty shim in the reference",
    "notebook/__init__.py": "notebook display helpers",
}

# per-name waivers: reference public names deliberately not carried,
# reason on record
WAIVED = {
    ("test_utils", "get_mnist"): "no-egress environment: downloads banned",
}


def public_names(path):
    """Top-level public defs/classes/assignment-aliases of a module."""
    tree = ast.parse(open(path, errors="replace").read())
    names = set()
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.ClassDef)):
            if not node.name.startswith("_"):
                names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and not t.id.startswith("_") \
                        and t.id.isidentifier() and not t.id.isupper():
                    # alias like `GRUCell = ...`; skip CONSTANTS
                    if isinstance(node.value, (ast.Name, ast.Attribute,
                                               ast.Call, ast.Lambda)):
                        names.add(t.id)
    return names


def resolve(dotted):
    import importlib

    try:
        return importlib.import_module("mxnet_tpu." + dotted)
    except ImportError:
        import mxnet_tpu

        obj = mxnet_tpu
        for part in dotted.split("."):
            obj = getattr(obj, part, None)
            if obj is None:
                return None
        return obj


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()

    # static audit: no device work — force the CPU platform so importing
    # the package can't block on a tunneled accelerator backend
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import mxnet_tpu  # noqa: F401

    base = os.path.join(args.ref, "python", "mxnet")
    missing = []
    total = covered = waived = 0
    for rel, ours in sorted(MODULE_MAP.items()):
        ref_path = os.path.join(base, rel)
        if not os.path.exists(ref_path):
            continue
        mod = resolve(ours)
        if mod is None:
            missing.append((rel, "<module %s>" % ours))
            continue
        for name in sorted(public_names(ref_path)):
            total += 1
            if hasattr(mod, name):
                covered += 1
            elif (ours.split(".")[-1], name) in WAIVED:
                waived += 1
                if args.verbose:
                    print("waived: %s.%s (%s)" % (
                        ours, name, WAIVED[(ours.split(".")[-1], name)]))
            else:
                missing.append((rel, name))

    print("reference public frontend names: %d" % total)
    print("covered: %d   waived: %d" % (covered, waived))
    if missing:
        print("MISSING (%d):" % len(missing))
        for rel, name in missing:
            print("   %-28s %s" % (rel, name))
        return 1
    print("OK: zero unexplained misses")
    return 0


if __name__ == "__main__":
    sys.exit(main())
