"""group2ctx model parallelism tests (reference tier:
``tests/python/unittest/test_model_parallel.py`` — ctx_group attrs +
group2ctx bind place parts of one graph on different devices)."""

import jax
import numpy as np
import pytest

import mxnet_tpu as mx


def _two_cpus():
    if len(jax.devices()) < 2:
        pytest.skip("need 2 devices")
    return mx.cpu(0), mx.cpu(1)


def _net():
    with mx.AttrScope(ctx_group="dev1"):
        data = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
        h = mx.sym.Activation(h, act_type="tanh", name="act1")
    with mx.AttrScope(ctx_group="dev2"):
        h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
        out = mx.sym.LinearRegressionOutput(h, mx.sym.Variable("label"),
                                            name="out")
    return out


def test_group2ctx_forward_matches_single_device():
    c0, c1 = _two_cpus()
    net = _net()
    rng = np.random.RandomState(0)
    arrays = {
        "data": rng.randn(3, 5).astype(np.float32),
        "fc1_weight": rng.randn(8, 5).astype(np.float32),
        "fc1_bias": np.zeros(8, np.float32),
        "fc2_weight": rng.randn(4, 8).astype(np.float32),
        "fc2_bias": np.zeros(4, np.float32),
        "label": rng.randn(3, 4).astype(np.float32),
    }

    def bind(group2ctx):
        args = {k: mx.nd.array(v) for k, v in arrays.items()}
        grads = {k: mx.nd.zeros(v.shape) for k, v in arrays.items()
                 if k not in ("data", "label")}
        return net.bind(c0, args, args_grad=grads, group2ctx=group2ctx)

    ex_mp = bind({"dev1": c0, "dev2": c1})
    assert ex_mp._placed, "expected placed execution across devices"
    ex_sd = bind(None)
    out_mp = ex_mp.forward(is_train=False)[0].asnumpy()
    out_sd = ex_sd.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out_mp, out_sd, rtol=1e-5, atol=1e-6)


def test_group2ctx_training_grads_match():
    c0, c1 = _two_cpus()
    net = _net()
    rng = np.random.RandomState(1)
    arrays = {
        "data": rng.randn(4, 5).astype(np.float32),
        "fc1_weight": rng.randn(8, 5).astype(np.float32) * 0.3,
        "fc1_bias": np.zeros(8, np.float32),
        "fc2_weight": rng.randn(4, 8).astype(np.float32) * 0.3,
        "fc2_bias": np.zeros(4, np.float32),
        "label": rng.randn(4, 4).astype(np.float32),
    }

    grads = {}
    for mode, g2c in (("mp", {"dev1": c0, "dev2": c1}), ("sd", None)):
        args = {k: mx.nd.array(v) for k, v in arrays.items()}
        gdict = {k: mx.nd.zeros(v.shape) for k, v in arrays.items()
                 if k not in ("data", "label")}
        ex = net.bind(c0, args, args_grad=gdict, group2ctx=g2c)
        ex.forward(is_train=True)
        ex.backward()
        grads[mode] = {k: v.asnumpy() for k, v in gdict.items()}

    for k in grads["sd"]:
        np.testing.assert_allclose(grads["mp"][k], grads["sd"][k],
                                   rtol=1e-4, atol=1e-6, err_msg=k)
