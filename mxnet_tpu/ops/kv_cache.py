"""Block-allocated paged KV cache for autoregressive decode.

The vLLM idea (Kwon et al., SOSP '23) sized for this runtime: instead of
one contiguous ``[max_len, heads, dim]`` buffer per sequence — which
fragments HBM and caps concurrency at ``pool / max_len`` — K/V state
lives in fixed-size **token blocks** drawn from a shared pool.  Each
sequence owns an ordered **block table** (list of block ids); logical
token position ``t`` lives at ``(table[t // block_size], t % block_size)``.
Allocation is a free-list pop, release is a free-list push, and a full
pool surfaces as the typed :class:`CacheExhaustedError` (HTTP 429)
through the serving admission machinery rather than an OOM.

Layout: one pair of pools per cache, shaped

    ``k_pages / v_pages : [num_layers, num_blocks, block_size, heads, dim]``

so a decode step can ship the *whole* pool to the device plus per-batch
``int32`` block tables, and :func:`~mxnet_tpu.ops.attention.
paged_decode_attention` gathers K/V rows through the table inside the
jitted step — the pool shape is static, so decode dispatches never
recompile as sequences come and go.

The cache is **backend state**: ``serving.generation.LMBackend`` owns
one, the ``ModelRegistry`` swap machinery replaces cache and weights
together, and the generation lane re-prefills live sequences after a
hot-swap (stale pages are never mixed with new weights).

Chaos site ``serving.kv_alloc`` fires at the top of :meth:`allocate`
(name = sequence id) so tests can drill the exhaustion/429 path and
allocation delay without filling the pool.
"""

from __future__ import annotations

import os
import threading
import weakref

import numpy as np

from .. import chaos
from ..base import MXNetError
from ..observability import memory as _memory
from ..observability import metrics as _metrics

__all__ = ["CacheExhaustedError", "PagedKVCache", "default_block_size",
           "default_num_blocks"]


class CacheExhaustedError(MXNetError):
    """No free KV-cache blocks for a new sequence or a grown one.

    Carries ``http_status = 429`` so the serving front-end maps it like
    the other typed admission rejections (the client should back off and
    retry; accepted sequences are never evicted to make room).
    """

    http_status = 429


def default_block_size():
    """Tokens per cache block (``MXNET_TPU_GEN_BLOCK_SIZE``, default 16)."""
    return int(os.environ.get("MXNET_TPU_GEN_BLOCK_SIZE", "16"))


def default_num_blocks():
    """Blocks in the shared pool (``MXNET_TPU_GEN_BLOCKS``, default 64)."""
    return int(os.environ.get("MXNET_TPU_GEN_BLOCKS", "64"))


_M_OCC = _metrics.gauge(
    "serving_kv_cache_occupancy",
    "Fraction of KV-cache blocks in use, by model", ["model"])
_M_BLOCKS = _metrics.gauge(
    "serving_kv_cache_used_blocks",
    "KV-cache blocks currently allocated, by model", ["model"])
_M_EXHAUSTED = _metrics.counter(
    "serving_kv_cache_exhausted_total",
    "Allocations rejected because the block pool was empty, by model",
    ["model"])
_M_HEADROOM = _metrics.gauge(
    "serving_kv_cache_headroom",
    "Fraction of KV-cache blocks still free (1 - occupancy), by model",
    ["model"])
_M_FRAG = _metrics.gauge(
    "serving_kv_cache_fragmentation",
    "Internal fragmentation of allocated blocks: 1 - tokens_written / "
    "(used_blocks * block_size); 0 when nothing is allocated, by model",
    ["model"])
_M_ALLOCS = _metrics.counter(
    "serving_kv_cache_alloc_blocks_total",
    "Blocks handed out by the free list, by model", ["model"])
_M_FREES = _metrics.counter(
    "serving_kv_cache_free_blocks_total",
    "Blocks returned to the free list, by model", ["model"])
_M_SESS_BLOCKS = _metrics.histogram(
    "serving_kv_blocks_per_session",
    "Blocks one sequence held when it was freed, by model", ["model"],
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))


class PagedKVCache(object):
    """Free-list block allocator + per-sequence block tables + the pools.

    Thread-safe: the generation lane allocates/frees from its loop
    thread while the front-end frees on client disconnect.  All index
    math is host-side numpy; the pools are plain ``np.ndarray`` so the
    dispatch path hands them to jit as-is (XLA:CPU aliases the buffer,
    device backends stage them once per step).
    """

    def __init__(self, num_layers, num_heads, head_dim, block_size=None,
                 num_blocks=None, dtype=np.float32, model="default"):
        self.block_size = int(block_size or default_block_size())
        self.num_blocks = int(num_blocks or default_num_blocks())
        if self.block_size <= 0 or self.num_blocks <= 0:
            raise MXNetError("PagedKVCache needs positive block_size/"
                             "num_blocks (got %d/%d)"
                             % (self.block_size, self.num_blocks))
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.model = model
        shape = (self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        self.k_pages = np.zeros(shape, dtype=dtype)
        self.v_pages = np.zeros(shape, dtype=dtype)
        self._lock = threading.Lock()
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._tables = {}      # seq_id -> [block ids]
        self._lengths = {}     # seq_id -> tokens written
        self._occ = _M_OCC.labels(model)
        self._used = _M_BLOCKS.labels(model)
        self._exhausted = _M_EXHAUSTED.labels(model)
        self._headroom = _M_HEADROOM.labels(model)
        self._frag = _M_FRAG.labels(model)
        self._allocs = _M_ALLOCS.labels(model)
        self._frees = _M_FREES.labels(model)
        self._sess_blocks = _M_SESS_BLOCKS.labels(model)
        # book the host-resident page pools into the memory ledger;
        # the finalizer releases the row when the cache (hot-swap,
        # backend teardown) is collected
        self._ledger_key = id(self)
        _memory.tag("kv_cache", self._ledger_key,
                    self.k_pages.nbytes + self.v_pages.nbytes,
                    device="host")
        weakref.finalize(self, _memory.untag, "kv_cache",
                         self._ledger_key)

    # -- allocation --------------------------------------------------

    def _blocks_for(self, num_tokens):
        return -(-max(num_tokens, 1) // self.block_size)

    def allocate(self, seq_id, num_tokens):
        """Reserve capacity for ``num_tokens`` total tokens of ``seq_id``.

        Idempotent growth: call again with a larger total to extend.
        Raises :class:`CacheExhaustedError` (and allocates nothing) if
        the free list cannot cover the extension — a failed grow never
        strands partially-allocated blocks.
        """
        chaos.visit("serving.kv_alloc", name=str(seq_id))
        need_total = self._blocks_for(num_tokens)
        with self._lock:
            table = self._tables.get(seq_id, [])
            grow = need_total - len(table)
            if grow > len(self._free):
                self._exhausted.inc()
                used = self.num_blocks - len(self._free)
                err = CacheExhaustedError(
                    "kv cache exhausted: seq %r needs %d more block(s), "
                    "%d free of %d" % (seq_id, grow, len(self._free),
                                       self.num_blocks))
                # occupancy hints the serving front-end forwards in the
                # 429 error body so clients can back off proportionally
                err.kv_cache_occupancy = used / float(self.num_blocks)
                err.kv_cache_blocks_free = len(self._free)
                err.kv_cache_blocks_total = self.num_blocks
                raise err
            if grow > 0:
                fresh = [self._free.pop() for _ in range(grow)]
                self._tables[seq_id] = table + fresh
                self._lengths.setdefault(seq_id, 0)
                self._allocs.inc(grow)
            self._set_gauges_locked()

    def free(self, seq_id):
        """Return ``seq_id``'s blocks to the pool; returns the freed
        block ids (empty for an unknown sequence — freeing is always
        safe to call from retire paths)."""
        with self._lock:
            table = self._tables.pop(seq_id, None) or []
            self._lengths.pop(seq_id, None)
            if table:
                self._free.extend(reversed(table))
                self._frees.inc(len(table))
                self._sess_blocks.observe(len(table))
            self._set_gauges_locked()
            return list(table)

    def _set_gauges_locked(self):
        used = self.num_blocks - len(self._free)
        self._used.set(used)
        self._occ.set(used / float(self.num_blocks))
        self._headroom.set(len(self._free) / float(self.num_blocks))
        if used:
            written = sum(self._lengths.values())
            self._frag.set(1.0 - written / float(used * self.block_size))
        else:
            self._frag.set(0.0)

    # -- reads -------------------------------------------------------

    def length(self, seq_id):
        return self._lengths.get(seq_id, 0)

    def sequences(self):
        with self._lock:
            return sorted(self._tables)

    def block_table(self, seq_id, max_blocks):
        """Padded ``int32[max_blocks]`` table for a decode dispatch.

        Pad entries point at block 0 — harmless, because decode
        attention masks scores past the context length before softmax
        (``-1e30`` → exp underflows to exact ``0.0``), so whatever those
        rows hold never reaches the output bits.
        """
        table = self._tables.get(seq_id)
        if table is None:
            raise MXNetError("unknown sequence %r" % (seq_id,))
        if len(table) > max_blocks:
            raise MXNetError(
                "sequence %r spans %d blocks > table width %d"
                % (seq_id, len(table), max_blocks))
        out = np.zeros(max_blocks, dtype=np.int32)
        out[:len(table)] = table
        return out

    # -- writes ------------------------------------------------------

    def write_prefill(self, seq_id, k, v):
        """Store prompt K/V: ``k``/``v`` shaped ``[L, T, heads, dim]``.

        Requires a prior :meth:`allocate` covering ``T`` tokens.  Writes
        happen only after a successful prefill dispatch, so a retried
        (chaos-dropped) dispatch never leaves half-written pages.
        """
        k = np.asarray(k)
        num = k.shape[1]
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None or len(table) < self._blocks_for(num):
                raise MXNetError(
                    "write_prefill(%r, %d tokens) exceeds allocation"
                    % (seq_id, num))
            for t in range(num):
                blk, off = table[t // self.block_size], t % self.block_size
                self.k_pages[:, blk, off] = k[:, t]
                self.v_pages[:, blk, off] = np.asarray(v)[:, t]
            self._lengths[seq_id] = max(self._lengths.get(seq_id, 0), num)

    def write_token(self, seq_id, pos, k, v):
        """Store one decoded token's K/V: ``k``/``v`` ``[L, heads, dim]``."""
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None or pos >= len(table) * self.block_size:
                raise MXNetError(
                    "write_token(%r, pos=%d) exceeds allocation"
                    % (seq_id, pos))
            blk, off = table[pos // self.block_size], pos % self.block_size
            self.k_pages[:, blk, off] = np.asarray(k)
            self.v_pages[:, blk, off] = np.asarray(v)
            self._lengths[seq_id] = max(self._lengths.get(seq_id, 0),
                                        pos + 1)

    # -- introspection ----------------------------------------------

    def stats(self):
        with self._lock:
            used = self.num_blocks - len(self._free)
            written = sum(self._lengths.values())
            return {"blocks": self.num_blocks, "used": used,
                    "free": len(self._free),
                    "occupancy": used / float(self.num_blocks),
                    "headroom": len(self._free) / float(self.num_blocks),
                    "fragmentation": (1.0 - written
                                      / float(used * self.block_size))
                                     if used else 0.0,
                    "sequences": len(self._tables),
                    "block_size": self.block_size,
                    "pool_bytes": self.k_pages.nbytes
                                  + self.v_pages.nbytes}
