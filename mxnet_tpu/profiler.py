"""Profiler (parity: reference ``python/mxnet/profiler.py`` +
``src/engine/profiler.cc``).

Two lanes, merged under one API:
 - **device**: the jax/XLA profiler (xplane) — ``profiler_set_state('run')``
   starts a trace viewable in TensorBoard/Perfetto.  This is the TPU
   equivalent of the reference's GPU op timing.
 - **host engine**: the native engine profiler (``native/src/profiler.cc``)
   records per-op start/end/thread for host-side engine work and dumps
   chrome://tracing JSON — the direct equivalent of the reference's
   ``OprExecStat`` → ``DumpProfile`` path
   (``src/engine/profiler.h:20-141``, hook ``threaded_engine.h:294-308``).
"""

from __future__ import annotations

import logging
import os
import threading
import time

from . import _native

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "scope"]

_STATE = {"mode": "symbolic", "dir": "profile_output", "running": False}


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """(parity: ``profiler.py:profiler_set_config``)"""
    _STATE["mode"] = mode
    _STATE["dir"] = os.path.splitext(filename)[0]


def profiler_set_state(state="stop"):
    """'run' starts the xplane trace + native engine recording; 'stop' ends
    both (parity: ``profiler.py:profiler_set_state``)."""
    import jax

    lib = _native.lib()
    if state == "run" and not _STATE["running"]:
        os.makedirs(_STATE["dir"], exist_ok=True)
        jax.profiler.start_trace(_STATE["dir"])
        if lib is not None:
            lib.mxtpu_profiler_clear()  # fresh session, drop stale events
            lib.mxtpu_profiler_set_state(1)
        _STATE["running"] = True
    elif state == "stop" and _STATE["running"]:
        jax.profiler.stop_trace()
        if lib is not None:
            lib.mxtpu_profiler_set_state(0)
        _STATE["running"] = False
    else:
        logging.debug("profiler state change to %r ignored", state)


def dump_profile():
    """Stop + flush both traces; the host-engine chrome trace lands at
    ``<dir>/engine_trace.json`` (parity: ``profiler.py:dump_profile`` /
    ``Profiler::DumpProfile``)."""
    profiler_set_state("stop")
    lib = _native.lib()
    if lib is not None:
        os.makedirs(_STATE["dir"], exist_ok=True)
        path = os.path.join(_STATE["dir"], "engine_trace.json")
        n = lib.mxtpu_profiler_dump(path.encode())
        logging.info("dumped %d engine events to %s", n, path)
        return path
    return None


class scope(object):
    """Context manager recording a named frontend span into the host trace
    (the ``mx.profiler``-visible analog of engine op events)."""

    def __init__(self, name, cat="frontend"):
        self.name = name
        self.cat = cat

    def __enter__(self):
        self._t0 = int(time.monotonic() * 1e6)
        return self

    def __exit__(self, *exc):
        lib = _native.lib()
        if lib is not None and lib.mxtpu_profiler_state():
            lib.mxtpu_profiler_add_event(
                self.name.encode(), self.cat.encode(), self._t0,
                int(time.monotonic() * 1e6), threading.get_ident() % 100000)
        return False
