"""``make fairness`` / ``python tools/loadgen.py``: the multi-tenant
robustness drill.

A self-contained synthetic load generator proving the PR-16 fairness
contract the repo's way — drive the real stack, assert on the real
metrics, exit non-zero on any miss.  Four acts, a few seconds on CPU:

1. **Fairness under heavy-tailed skew.**  Three tenants hammer one
   numpy-backed replica group — ``bulk`` sends ~8× the load of
   ``gold`` and ``silver`` (the heavy tail) and holds a tight
   requests/s quota.  Assert: ``bulk`` is shed with typed per-tenant
   429s (``QuotaExceededError``, ``serving_rejected_total{reason=
   "quota",tenant="bulk"}``) while ``gold``'s p99 stays inside its SLO
   — overload degrades per tenant, never globally.
2. **Zero dropped accepted work across elastic scale.**  One
   ``grow(1)`` and one ``shrink(1)`` land mid-load; every request the
   group *accepted* must answer (the PR-8/PR-11 brownout contract,
   now under multi-tenant queues).
3. **KV-affinity routing.**  A tiny LM replica group behind
   :class:`~mxnet_tpu.serving.KVAffinityRouter`, with a seeded
   ``serving.route`` chaos rule knocking candidates out of rotation:
   assert ``kv_affinity_hit_ratio`` ends > 0, and that a session
   forced off its home replica re-prefills to a **bitwise-identical**
   token stream (a spill costs latency, never correctness).
4. **Per-tenant budgets federate.**  Run the SLO report over the
   process registry, then a :class:`~mxnet_tpu.observability.
   federation` pass, and assert ``slo_error_budget_remaining{slo,
   tenant}`` rows ride the federated exposition.

Knobs (env): ``LOADGEN_REQUESTS`` (default 240 fairness requests),
``LOADGEN_SEED`` (chaos + skew seed, default 16).
"""

import os
import random
import sys
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_TPU_METRICS", "1")

import numpy as np                                    # noqa: E402

from mxnet_tpu import chaos, serving                  # noqa: E402
from mxnet_tpu import observability as obs            # noqa: E402
from mxnet_tpu.observability import metrics as omet   # noqa: E402
from mxnet_tpu.observability import slo as oslo       # noqa: E402

FAILURES = []


def check(ok, what):
    tag = "ok  " if ok else "FAIL"
    print("  [%s] %s" % (tag, what))
    if not ok:
        FAILURES.append(what)


class _SlowEcho(serving.Backend):
    """Numpy backend with a tiny fixed service time, so queues actually
    form and fairness is observable."""

    input_shapes = {"data": (4,)}

    def __init__(self, delay_s=0.002):
        self.delay_s = delay_s

    def infer(self, batch):
        time.sleep(self.delay_s)
        return [batch["data"] * 2.0], False


def _fairness_and_scale(n_requests, seed):
    print("== fairness under heavy-tailed skew + elastic scale ==")
    group = serving.ReplicaGroup(replicas=2, group="fairpool")
    group.register("mlp", lambda: _SlowEcho(), buckets=[1, 2, 4, 8])
    group.tenant_policy.set_weight("gold", 3.0)
    group.tenant_policy.set_weight("silver", 1.0)
    # the saturating tenant: weight 1 AND a tight request budget
    group.tenant_policy.set_quota("bulk", rps=20.0)
    router = serving.ServingRouter(group)

    rng = random.Random(seed)
    # heavy tail: bulk is ~80% of offered load
    tenants = ["bulk"] * 8 + ["gold", "silver"]
    lat = {"gold": [], "silver": [], "bulk": []}
    sheds = {"bulk": 0, "gold": 0, "silver": 0}
    dropped = []           # accepted-but-unanswered: must stay empty
    lock = threading.Lock()
    row = {"data": np.ones(4, np.float32)}

    def one(tenant):
        t0 = time.monotonic()
        try:
            router.request("mlp", row, tenant=tenant, timeout=30.0)
        except serving.QuotaExceededError as exc:
            with lock:
                sheds[tenant] += 1
            assert exc.http_status == 429
            return
        except serving.ServerOverloadedError:
            with lock:
                sheds[tenant] += 1
            return
        except Exception as exc:       # accepted work must never die
            with lock:
                dropped.append("%s: %r" % (tenant, exc))
            return
        with lock:
            lat[tenant].append(time.monotonic() - t0)

    threads = []
    grew = shrunk = False
    for i in range(n_requests):
        tenant = tenants[rng.randrange(len(tenants))]
        th = threading.Thread(target=one, args=(tenant,))
        th.start()
        threads.append(th)
        if i == n_requests // 3 and not grew:
            grow = group.grow(1)
            grew = True
            print("  grow mid-load:", grow)
        if i == (2 * n_requests) // 3 and not shrunk:
            shrink = group.shrink(1, timeout=30.0)
            shrunk = True
            print("  shrink mid-load:", shrink)
        time.sleep(0.001)
    for th in threads:
        th.join(timeout=60.0)

    def p99(xs):
        if not xs:
            return float("nan")
        return sorted(xs)[min(len(xs) - 1, int(0.99 * len(xs)))]

    gold_p99 = p99(lat["gold"])
    slo_s = float(os.environ.get("LOADGEN_SLO_S", "0.5"))
    print("  answered: gold=%d silver=%d bulk=%d; quota sheds bulk=%d"
          % (len(lat["gold"]), len(lat["silver"]), len(lat["bulk"]),
             sheds["bulk"]))
    print("  gold p99 = %.1f ms (SLO %.0f ms)"
          % (gold_p99 * 1e3, slo_s * 1e3))
    check(grew and shrunk, "one grow and one shrink landed mid-load")
    check(not dropped, "zero accepted requests dropped across scale "
                       "events%s" % ("" if not dropped
                                     else ": " + "; ".join(dropped[:3])))
    check(sheds["bulk"] > 0,
          "saturating tenant shed with typed per-tenant 429s "
          "(%d quota sheds)" % sheds["bulk"])
    check(len(lat["gold"]) > 0 and gold_p99 <= slo_s,
          "innocent tenant p99 inside SLO under saturation")
    rej = omet.REGISTRY.get("serving_rejected_total")
    check(rej.labels("mlp", "quota", "bulk").value > 0,
          "sheds booked in serving_rejected_total{reason=quota,"
          "tenant=bulk}")
    group.close()
    return sheds, lat


def _affinity(seed):
    print("== KV-affinity routing under seeded serving.route chaos ==")
    from mxnet_tpu.models import transformer as tfm

    cfg = tfm.lm_config(num_classes=64, seq_len=48, num_embed=16,
                        num_heads=2, num_layers=2)
    params = tfm.init_lm_params(cfg, seed=0)
    group = serving.ReplicaGroup(
        replicas=2, group="genpool",
        scheduler_cls=serving.GenerationScheduler)
    group.register("lm", lambda: serving.LMBackend(
        params, cfg, block_size=4, num_blocks=64))
    router = serving.KVAffinityRouter(group)
    prompt = np.arange(1, 9, dtype=np.int32)

    # the cold reference: a sessionless one-shot generation
    cold = router.generate("lm", prompt, max_new_tokens=6, timeout=120)

    # seeded chaos: every ~3rd routing candidate is unroutable — the
    # drill for spill / re-home without ever dropping work
    chaos.clear()
    chaos.inject("serving.route", "drop", prob=0.34, seed=seed)
    streams = []
    for i in range(12):
        session = "s%d" % (i % 3)       # 3 sticky sessions, revisited
        streams.append(router.generate("lm", prompt, max_new_tokens=6,
                                       session=session, tenant="gold",
                                       timeout=120))
    chaos.clear()
    check(all(s == cold for s in streams),
          "12/12 chaos-routed generations bitwise-equal to the cold "
          "session (re-prefill spill is correctness-free)")
    ratio = omet.REGISTRY.get("kv_affinity_hit_ratio")
    val = ratio.labels("genpool").value
    print("  kv_affinity_hit_ratio = %.3f (hits %d / lookups %d)"
          % (val, router._hits, router._lookups))
    check(val > 0, "kv_affinity_hit_ratio > 0 with affinity on")
    route = omet.REGISTRY.get("serving_route_total")
    outcomes = {o: route.labels("genpool", o).value
                for o in ("hit", "miss", "spill", "dead", "failover")}
    print("  serving_route_total:", outcomes)
    group.close()
    return outcomes


def _federated_budgets():
    print("== per-tenant error budgets federate ==")
    report = oslo.report()           # sets the {slo, tenant} gauges
    avail = [r for r in report["slos"]
             if r["slo"] == "availability"][0]
    check("tenants" in avail and "bulk" in avail["tenants"],
          "/slo report carries per-tenant availability rows")
    out = obs.federate([{"shard": 0, "role": "serving", "epoch": 1,
                         "registry": omet.REGISTRY}])
    rows = [l for l in out.splitlines()
            if l.startswith("slo_error_budget_remaining{")]
    per_tenant = [l for l in rows
                  if 'tenant="all"' not in l and "tenant=" in l]
    for l in rows[:6]:
        print("  " + l)
    check(any('tenant="all"' in l for l in rows),
          "aggregate budget row federates")
    check(len(per_tenant) > 0,
          "per-tenant slo_error_budget_remaining rows federate")


def main():
    n = int(os.environ.get("LOADGEN_REQUESTS", "240"))
    seed = int(os.environ.get("LOADGEN_SEED", "16"))
    t0 = time.monotonic()
    _fairness_and_scale(n, seed)
    _affinity(seed)
    _federated_budgets()
    dt = time.monotonic() - t0
    if FAILURES:
        print("\nFAIL (%d): %s  [%.1fs]" % (len(FAILURES),
                                            "; ".join(FAILURES), dt))
        return 1
    print("\nfairness drill PASS  [%.1fs]" % dt)
    return 0


if __name__ == "__main__":
    sys.exit(main())
