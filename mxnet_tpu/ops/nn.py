"""Neural-net layer operators.

Parity targets: the reference's legacy ``OperatorProperty`` layers
(``src/operator/*-inl.h``: FullyConnected, Convolution, Pooling, BatchNorm,
Activation, Dropout, SoftmaxOutput, ...).  Where the reference dispatches to
cuDNN fast paths (``src/operator/cudnn_*-inl.h``), here the same layer lowers
to XLA ops (``lax.conv_general_dilated``, ``lax.reduce_window``) that hit the
TPU MXU/VPU directly — the compiler plays cuDNN's role.

Loss layers (SoftmaxOutput, regression outputs, MakeLoss) replicate the
reference's semantics that ``backward()`` needs no head gradient: they are
``jax.custom_vjp`` rules that *ignore* the incoming cotangent, exactly as the
reference's loss-layer Backward ignores ``out_grad``
(``src/operator/softmax_output-inl.h``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as _np

from .registry import ParamSpec as P
from .registry import register

# ----------------------------------------------------------------------
# FullyConnected (reference src/operator/fully_connected-inl.h:76-84:
# out = dot(data, W.T) + b) — lowers to a single MXU matmul.
# ----------------------------------------------------------------------


def _fc_input_names(attrs):
    return ["data", "weight"] if attrs.get("no_bias") else ["data", "weight", "bias"]


@register(
    "FullyConnected",
    arg_names=["data", "weight", "bias"],
    input_names_fn=_fc_input_names,
    params={
        "num_hidden": P("int", 0, required=True),
        "no_bias": P("bool", False),
        "flatten": P("bool", True),
    },
)
def _fully_connected(attrs, data, weight, bias=None):
    if attrs["flatten"] and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    # mixed precision: fp32 master weights cast to the activation dtype at
    # use; the MXU accumulates fp32 (preferred_element_type)
    weight = weight.astype(data.dtype)
    # NB: no preferred_element_type — the TPU MXU accumulates fp32 for bf16
    # operands anyway, and a widened primal output breaks the conv/dot
    # transpose (f32 cotangent vs bf16 operand) under vjp.  fp16 (whose
    # accumulation is NOT guaranteed fp32 on all backends) computes in fp32.
    if data.dtype == jnp.float16:
        out = jax.lax.dot_general(
            data.astype(jnp.float32), weight.astype(jnp.float32),
            (((data.ndim - 1,), (1,)), ((), ()))).astype(jnp.float16)
    else:
        out = jax.lax.dot_general(
            data, weight, (((data.ndim - 1,), (1,)), ((), ())))
    if not attrs["no_bias"]:
        out = out + bias.astype(data.dtype)
    return out


# ----------------------------------------------------------------------
# Convolution / Deconvolution (reference convolution-inl.h, cudnn_convolution)
# ----------------------------------------------------------------------


def _conv_dims(kernel):
    return len(kernel)


def _conv_dnums(nd):
    # NC[DHW] activations, OI[DHW] weights
    spatial = "DHW"[-nd:] if nd <= 3 else None
    lhs = "NC" + spatial
    rhs = "OI" + spatial
    return (lhs, rhs, lhs)


def _conv1x1_pick_bm(M):
    for bm in (4096, 2048, 1024, 512, 256, 128):
        if M % bm == 0:
            return bm
    return None


def _conv1x1_dgrad_pallas(dy2, wio, out_dtype, bm):
    """dx = dy @ w as one Pallas MXU pass over row blocks: dy2 [M, O]
    times wio [O, I] -> [M, I].  The r5 roofline probe
    (tools/bottleneck_probe.py) measured XLA's 1x1 transposed-conv dgrad
    at ~2-3x the stream floor at ResNet bottleneck shapes; this matmul
    formulation is the experiment's positive arm."""
    import jax.experimental.pallas as pl

    M, O = dy2.shape
    I = wio.shape[1]

    def kern(dy_ref, w_ref, o_ref):
        acc = jnp.dot(dy_ref[...], w_ref[...],
                      preferred_element_type=jnp.float32)
        o_ref[...] = acc.astype(o_ref.dtype)

    return pl.pallas_call(
        kern, grid=(M // bm,),
        in_specs=[pl.BlockSpec((bm, O), lambda i: (i, 0)),
                  pl.BlockSpec((O, I), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bm, I), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((M, I), out_dtype),
        interpret=jax.default_backend() != "tpu")(dy2, wio)


@jax.custom_vjp
def _conv1x1_nhwc(x, w):
    """1x1 stride-1 NHWC conv with a hand-rolled backward (experiment
    surface for the ResNet roofline attack; MXTPU_CONV1X1 selects the
    backward implementation: 'dot' = dot_general dgrad+wgrad,
    'pallas' = Pallas dgrad + dot wgrad; forward stays XLA's conv,
    which already fuses its BN/ReLU/residual epilogue consumers)."""
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), "VALID",
        dimension_numbers=("NHWC", "OHWI", "NHWC"))


def _conv1x1_fwd(x, w):
    return _conv1x1_nhwc(x, w), (x, w)


def _conv1x1_bwd(res, dy):
    import os

    x, w = res
    mode = os.environ.get("MXTPU_CONV1X1", "dot")
    if mode not in ("dot", "pallas"):
        from ..base import MXNetError

        raise MXNetError(
            "MXTPU_CONV1X1=%r is not a backward mode (valid: 'default' "
            "or unset = XLA conv, 'dot', 'pallas'); refusing to guess — "
            "a silent fallback would mislabel a benchmark" % mode)
    B, H, W_, I = x.shape
    O = w.shape[0]
    M = B * H * W_
    wio = w.reshape(O, I)  # OHWI, 1x1 kernel
    # wgrad: dw[o, i] = sum_m dy[m, o] * x[m, i] — a single MXU matmul
    # contracting the whole batch*spatial axis (the transposed-conv
    # formulation XLA uses pays layout copies instead)
    dw = jax.lax.dot_general(
        dy.reshape(M, O), x.reshape(M, I),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(w.dtype)
    bm = _conv1x1_pick_bm(M)
    if mode == "pallas" and bm is not None:
        dx2 = _conv1x1_dgrad_pallas(dy.reshape(M, O), wio, x.dtype, bm)
        dx = dx2.reshape(B, H, W_, I)
    else:
        dx = jax.lax.dot_general(
            dy, wio, (((3,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)
    return dx, dw.reshape(w.shape)


_conv1x1_nhwc.defvjp(_conv1x1_fwd, _conv1x1_bwd)


def _conv1x1_eligible(attrs, out_dtype, nd, stride, dilate, pad, nhwc):
    """NOTE: the env var is read at TRACE time — a jitted step keeps the
    mode it was traced with regardless of later env changes (jit caches
    don't key on env).  Benchmark each mode in a fresh process, as
    docs/PERF.md's round-5 table did."""
    import os

    if os.environ.get("MXTPU_CONV1X1", "") in ("", "default"):
        return False
    # out_dtype is the ORIGINAL dtype (fp16 is cast to f32 before this
    # runs; gate on what the user ran, not the upcast)
    return (nhwc and nd == 2 and tuple(attrs["kernel"]) == (1, 1)
            and tuple(stride) == (1, 1) and tuple(dilate) == (1, 1)
            and tuple(pad) == (0, 0) and attrs["num_group"] == 1
            and out_dtype in (jnp.bfloat16, jnp.float32))


@register(
    "Convolution",
    aliases=["Convolution_v1"],  # legacy pre-NNVM registration, same math
    arg_names=["data", "weight", "bias"],
    input_names_fn=_fc_input_names,
    params={
        "kernel": P("shape", None, required=True),
        "stride": P("shape", None),
        "dilate": P("shape", None),
        "pad": P("shape", None),
        "num_filter": P("int", 0, required=True),
        "num_group": P("int", 1),
        "workspace": P("int", 1024),
        "no_bias": P("bool", False),
        "cudnn_tune": P("str", None),
        "cudnn_off": P("bool", False),
        "layout": P("str", None, enum=["NCHW", "NCW", "NCDHW", "NHWC"]),
    },
)
def _convolution(attrs, data, weight, bias=None):
    nd = _conv_dims(attrs["kernel"])
    stride = attrs["stride"] or (1,) * nd
    dilate = attrs["dilate"] or (1,) * nd
    pad = attrs["pad"] or (0,) * nd
    nhwc = attrs.get("layout") == "NHWC" and nd == 2
    # mixed precision: fp32 master weights cast to the activation dtype;
    # bf16 accumulates fp32 on the MXU implicitly; fp16 (no such guarantee
    # on all backends) computes in fp32 and casts back — see the FC note
    out_dtype = data.dtype
    weight = weight.astype(out_dtype)
    if out_dtype == jnp.float16:
        data = data.astype(jnp.float32)
        weight = weight.astype(jnp.float32)
    if _conv1x1_eligible(attrs, out_dtype, nd, stride, dilate, pad, nhwc):
        out = _conv1x1_nhwc(data, weight).astype(out_dtype)
    else:
        out = jax.lax.conv_general_dilated(
            data,
            weight,
            window_strides=stride,
            padding=[(p, p) for p in pad],
            rhs_dilation=dilate,
            # NHWC: channels-last activations + OHWI weights — the
            # TPU-preferred layout (no relayout copies around each conv)
            dimension_numbers=("NHWC", "OHWI", "NHWC") if nhwc
            else _conv_dnums(nd),
            feature_group_count=attrs["num_group"],
        ).astype(out_dtype)
    if not attrs["no_bias"]:
        bias = bias.astype(out_dtype)
        out = out + (bias if nhwc else bias.reshape((1, -1) + (1,) * nd))
    return out


@register(
    "Deconvolution",
    arg_names=["data", "weight", "bias"],
    input_names_fn=_fc_input_names,
    params={
        "kernel": P("shape", None, required=True),
        "stride": P("shape", None),
        "dilate": P("shape", None),
        "pad": P("shape", None),
        "adj": P("shape", None),
        "target_shape": P("shape", None),
        "num_filter": P("int", 0, required=True),
        "num_group": P("int", 1),
        "workspace": P("int", 512),
        "no_bias": P("bool", True),
        "cudnn_tune": P("str", None),
        "cudnn_off": P("bool", False),
        "layout": P("str", None, enum=["NCHW", "NCW", "NCDHW", "NHWC"]),
    },
)
def _deconvolution(attrs, data, weight, bias=None):
    nd = _conv_dims(attrs["kernel"])
    stride = attrs["stride"] or (1,) * nd
    pad = attrs["pad"] or (0,) * nd
    adj = attrs["adj"] or (0,) * nd
    # transposed conv = gradient of conv wrt its input: lhs-dilated conv with
    # flipped IO[DHW]->OI[DHW] kernel
    k = attrs["kernel"]
    padding = [
        (k[i] - 1 - pad[i], k[i] - 1 - pad[i] + adj[i]) for i in range(nd)
    ]
    out_dtype = data.dtype
    weight = weight.astype(out_dtype)
    if out_dtype == jnp.float16:  # see the FC fp16 note
        data = data.astype(jnp.float32)
        weight = weight.astype(jnp.float32)
    w = jnp.swapaxes(weight, 0, 1)  # (in, out/g, *k) -> (out/g, in, *k)... see below
    # weight layout for Deconvolution in the reference is (in_ch, out_ch/g, *k)
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    out = jax.lax.conv_general_dilated(
        data,
        w,
        window_strides=(1,) * nd,
        padding=padding,
        lhs_dilation=stride,
        dimension_numbers=_conv_dnums(nd),
        feature_group_count=attrs["num_group"],
    ).astype(out_dtype)
    if not attrs["no_bias"] and bias is not None:
        out = out + bias.astype(out_dtype).reshape((1, -1) + (1,) * nd)
    return out


# ----------------------------------------------------------------------
# Pooling (reference pooling-inl.h) → lax.reduce_window
# ----------------------------------------------------------------------


@register(
    "Pooling",
    aliases=["Pooling_v1"],  # legacy pre-NNVM registration, same math
    params={
        "kernel": P("shape", None, required=True),
        "pool_type": P("str", "max", enum=["max", "avg", "sum"]),
        "global_pool": P("bool", False),
        "pooling_convention": P("str", "valid", enum=["valid", "full"]),
        "stride": P("shape", None),
        "pad": P("shape", None),
        "cudnn_off": P("bool", False),
        "layout": P("str", None, enum=["NCHW", "NCW", "NCDHW", "NHWC"]),
    },
)
def _pooling(attrs, data):
    nd = data.ndim - 2
    nhwc = attrs.get("layout") == "NHWC" and nd == 2
    spatial0 = 1 if nhwc else 2  # first spatial dim index
    if attrs["global_pool"]:
        axes = tuple(range(spatial0, spatial0 + nd))
        if attrs["pool_type"] == "max":
            out = jnp.max(data, axis=axes, keepdims=True)
        elif attrs["pool_type"] == "sum":
            out = jnp.sum(data, axis=axes, keepdims=True)
        else:
            out = jnp.mean(data, axis=axes, keepdims=True)
        return out
    kernel = attrs["kernel"]
    stride = attrs["stride"] or (1,) * nd
    pad = attrs["pad"] or (0,) * nd
    pads = []
    for i in range(nd):
        x, k, s, p = data.shape[spatial0 + i], kernel[i], stride[i], pad[i]
        if attrs["pooling_convention"] == "full":
            out_sz = int(_np.ceil((x + 2 * p - k) / s)) + 1
        else:
            out_sz = (x + 2 * p - k) // s + 1
        need = max((out_sz - 1) * s + k - x - p, p)
        pads.append((p, need))
    if nhwc:
        window = (1,) + tuple(kernel) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        padding = ((0, 0),) + tuple(pads) + ((0, 0),)
    else:
        window = (1, 1) + tuple(kernel)
        strides = (1, 1) + tuple(stride)
        padding = ((0, 0), (0, 0)) + tuple(pads)
    pt = attrs["pool_type"]
    if pt == "max":
        # scalar (not Array) init value so jax dispatches to the monoid
        # reduce_window_max primitive, which has a linearization rule
        init = -_np.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else _np.iinfo(data.dtype).min
        return jax.lax.reduce_window(
            data, init, jax.lax.max, window, strides, padding
        )
    summed = jax.lax.reduce_window(
        data, 0.0 if jnp.issubdtype(data.dtype, jnp.floating) else 0,
        jax.lax.add, window, strides, padding
    )
    if pt == "sum":
        return summed
    # avg: reference divides by full kernel volume (padding included)
    return summed / _np.prod(kernel)


# ----------------------------------------------------------------------
# Activation / LeakyReLU / Dropout
# ----------------------------------------------------------------------


@register(
    "Activation",
    params={
        "act_type": P(
            "str", "relu",
            enum=["relu", "sigmoid", "tanh", "softrelu", "softsign", "gelu"]
        )
    },
)
def _activation(attrs, x):
    t = attrs["act_type"]
    if t == "relu":
        return jax.nn.relu(x)
    if t == "gelu":  # transformer capability layer (absent in 2017 reference)
        return jax.nn.gelu(x)
    if t == "sigmoid":
        return jax.nn.sigmoid(x)
    if t == "tanh":
        return jnp.tanh(x)
    if t == "softrelu":
        return jax.nn.softplus(x)
    return jax.nn.soft_sign(x)


def _leaky_args(attrs):
    return ["data", "gamma"] if attrs.get("act_type") == "prelu" else ["data"]


@register(
    "LeakyReLU",
    arg_names=["data"],
    input_names_fn=_leaky_args,
    params={
        "act_type": P("str", "leaky", enum=["elu", "leaky", "prelu", "rrelu"]),
        "slope": P("float", 0.25),
        "lower_bound": P("float", 0.125),
        "upper_bound": P("float", 0.334),
    },
    needs_mode=True,
    needs_rng=True,
)
def _leaky_relu(attrs, x, gamma=None, is_train=False, rng=None):
    t = attrs["act_type"]
    if t == "leaky":
        return jnp.where(x > 0, x, attrs["slope"] * x)
    if t == "elu":
        return jnp.where(x > 0, x, attrs["slope"] * jnp.expm1(x))
    if t == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (x.ndim - 2)) if x.ndim > 1 else gamma
        return jnp.where(x > 0, x, g * x)
    # rrelu
    if is_train and rng is not None:
        slope = jax.random.uniform(
            rng, x.shape, minval=attrs["lower_bound"], maxval=attrs["upper_bound"]
        ).astype(x.dtype)
    else:
        slope = (attrs["lower_bound"] + attrs["upper_bound"]) / 2.0
    return jnp.where(x > 0, x, slope * x)


@register(
    "Dropout",
    params={"p": P("float", 0.5), "mode": P("str", "training")},
    needs_mode=True,
    needs_rng=True,
)
def _dropout(attrs, x, is_train=False, rng=None):
    p = attrs["p"]
    if not is_train or p <= 0.0 or rng is None:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


# ----------------------------------------------------------------------
# BatchNorm (reference batch_norm-inl.h).  args: data,gamma,beta;
# aux: moving_mean,moving_var (updated by training forward).
# ----------------------------------------------------------------------


@register(
    "BatchNorm",
    arg_names=["data", "gamma", "beta"],
    aux_names=["moving_mean", "moving_var"],
    params={
        "eps": P("float", 1e-3),
        "momentum": P("float", 0.9),
        "fix_gamma": P("bool", True),
        "use_global_stats": P("bool", False),
        "output_mean_var": P("bool", False),
        "cudnn_off": P("bool", False),
        "axis": P("int", 1),
    },
    needs_mode=True,
)
def _batch_norm(attrs, data, gamma, beta, moving_mean, moving_var, is_train=False):
    eps = attrs["eps"]
    mom = attrs["momentum"]
    # channel axis: 1 (NCHW default) or -1/ndim-1 for channels-last graphs
    ch = attrs.get("axis", 1) % data.ndim
    axes = tuple(i for i in range(data.ndim) if i != ch)
    bshape = tuple(-1 if i == ch else 1 for i in range(data.ndim))
    if attrs["fix_gamma"]:
        gamma = jax.lax.stop_gradient(jnp.ones_like(gamma))
    use_batch = is_train and not attrs["use_global_stats"]
    if use_batch:
        # SHIFTED single-pass stats: reduce E[x-s] and E[(x-s)^2] in ONE
        # fused read of the activation (XLA fuses sibling reductions over
        # the same operand), halving BN-stat HBM traffic vs the two-pass
        # mean->var form.  The shift s = running mean (free, per-channel,
        # tracks the true mean after warm-up) bounds the catastrophic
        # cancellation E[x^2]-E[x]^2 suffers when mean^2 >> var — e.g.
        # un-centered uint8-range inputs.  s is stop_gradient'd and exact
        # algebra: mean = s + E[x-s], var = E[(x-s)^2] - E[x-s]^2.
        shift = jax.lax.stop_gradient(moving_mean.astype(jnp.float32))
        xs = data.astype(jnp.float32) - shift.reshape(bshape)
        n = 1.0
        for i in axes:
            n *= data.shape[i]
        d_mean = jnp.sum(xs, axis=axes) / n
        mean = shift + d_mean
        var = jnp.sum(jnp.square(xs), axis=axes) / n - jnp.square(d_mean)
        var = jnp.maximum(var, 0.0)
        new_mm = mom * moving_mean + (1 - mom) * jax.lax.stop_gradient(mean)
        new_mv = mom * moving_var + (1 - mom) * jax.lax.stop_gradient(var)
    else:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    inv = jax.lax.rsqrt(var + eps)
    out = (data - mean.reshape(bshape).astype(data.dtype)) * (
        inv.reshape(bshape).astype(data.dtype)
    ) * gamma.reshape(bshape).astype(data.dtype) \
        + beta.reshape(bshape).astype(data.dtype)
    return out, new_mm, new_mv


# ----------------------------------------------------------------------
# Normalization cousins
# ----------------------------------------------------------------------


@register(
    "InstanceNorm",
    arg_names=["data", "gamma", "beta"],
    params={"eps": P("float", 1e-3)},
)
def _instance_norm(attrs, x, gamma, beta):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean) * jax.lax.rsqrt(var + attrs["eps"]) * gamma.reshape(
        bshape
    ) + beta.reshape(bshape)


@register(
    "L2Normalization",
    params={
        "eps": P("float", 1e-10),
        "mode": P("str", "instance", enum=["instance", "channel", "spatial"]),
    },
)
def _l2_normalization(attrs, x):
    mode = attrs["mode"]
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, x.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + attrs["eps"])
    return x / norm


@register(
    "LRN",
    params={
        "alpha": P("float", 1e-4),
        "beta": P("float", 0.75),
        "knorm": P("float", 2.0),
        "nsize": P("int", 5, required=True),
    },
)
def _lrn(attrs, x):
    n = attrs["nsize"]
    sq = jnp.square(x)
    # sum over a window of n channels centered at each channel
    pad = n // 2
    sq_pad = jnp.pad(sq, [(0, 0), (pad, pad)] + [(0, 0)] * (x.ndim - 2))
    acc = jnp.zeros_like(x)
    for i in range(n):
        acc = acc + jax.lax.dynamic_slice_in_dim(sq_pad, i, x.shape[1], axis=1)
    scale = attrs["knorm"] + (attrs["alpha"] / n) * acc
    return x / jnp.power(scale, attrs["beta"])


# ----------------------------------------------------------------------
# Loss layers — custom_vjp, head-grad independent (reference softmax_output-inl.h,
# regression_output-inl.h, make_loss-inl.h, svm_output-inl.h)
# ----------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _softmax_output_rule(grad_scale, ignore_label, multi_output, use_ignore,
                         preserve_shape, normalization, out_grad):
    @jax.custom_vjp
    def f(data, label):
        return _softmax_fwd(data)

    def _softmax_fwd(data):
        if multi_output:
            return jax.nn.softmax(data, axis=1)
        return jax.nn.softmax(
            data.reshape(data.shape[0], -1) if not preserve_shape else data, axis=-1
        ).reshape(data.shape)

    def fwd(data, label):
        out = _softmax_fwd(data)
        return out, (out, label)

    def bwd(res, g):
        out, label = res
        if multi_output:
            # data (n, c, *rest); label (n, *rest)
            lab = label.astype(jnp.int32)
            onehot = jnp.moveaxis(
                jax.nn.one_hot(lab, out.shape[1], dtype=out.dtype), -1, 1
            )
            grad = out - onehot
            valid = jnp.ones(lab.shape, dtype=out.dtype)
            if use_ignore:
                valid = (lab != int(ignore_label)).astype(out.dtype)
                grad = grad * valid[:, None]
        else:
            lab = label.reshape(-1).astype(jnp.int32)
            flat = out.reshape(out.shape[0], -1)
            onehot = jax.nn.one_hot(lab, flat.shape[1], dtype=out.dtype)
            grad = (flat - onehot).reshape(out.shape)
            valid = jnp.ones((out.shape[0],), dtype=out.dtype)
            if use_ignore:
                valid = (lab != int(ignore_label)).astype(out.dtype)
                grad = grad * valid.reshape((-1,) + (1,) * (out.ndim - 1))
        if normalization == "batch":
            grad = grad / out.shape[0]
        elif normalization == "valid":
            grad = grad / jnp.maximum(jnp.sum(valid), 1.0)
        grad = grad * grad_scale
        if out_grad:
            grad = grad * g
        return grad, jnp.zeros_like(label)

    f.defvjp(fwd, bwd)
    return f


@register(
    "SoftmaxOutput",
    aliases=["Softmax"],
    arg_names=["data", "label"],
    params={
        "grad_scale": P("float", 1.0),
        "ignore_label": P("float", -1.0),
        "multi_output": P("bool", False),
        "use_ignore": P("bool", False),
        "preserve_shape": P("bool", False),
        "normalization": P("str", "null", enum=["null", "batch", "valid"]),
        "out_grad": P("bool", False),
        "smooth_alpha": P("float", 0.0),
    },
)
def _softmax_output(attrs, data, label):
    rule = _softmax_output_rule(
        attrs["grad_scale"],
        attrs["ignore_label"],
        attrs["multi_output"],
        attrs["use_ignore"],
        attrs["preserve_shape"],
        attrs["normalization"],
        attrs["out_grad"],
    )
    return rule(data, label.astype(data.dtype))


@functools.lru_cache(maxsize=None)
def _fused_lm_head_rule(chunk):
    """Chunked fused linear + softmax cross-entropy (beyond-parity: the
    2017 reference predates LM heads; this is the long-context enabler).

    Computes per-token CE of ``logits = x @ W.T`` WITHOUT materializing
    the [T, V] logits: both passes stream T in ``chunk``-row slices via
    ``lax.scan``, so peak memory is O(chunk*V + d*V) instead of O(T*V)
    — at T=32k, V=32k that is the difference between 130 MB and 4.2 GB.
    Custom vjp (so the recompute is explicit, like the flash-attention
    backward): bwd recomputes each chunk's softmax and accumulates dW in
    fp32.  Matmuls run in the input dtype with fp32 accumulation
    (``preferred_element_type`` is safe here — no XLA transpose is ever
    taken of this op)."""

    @jax.custom_vjp
    def f(x, w, lab):
        return _loss(x, w, lab)

    def _dot_f32(a, b, dims):
        return jax.lax.dot_general(a, b, dims,
                                   preferred_element_type=jnp.float32)

    def _loss(x, w, lab):
        T, d = x.shape
        n = T // chunk
        xs = x.reshape(n, chunk, d)
        labs = lab.reshape(n, chunk).astype(jnp.int32)
        wl = w.astype(x.dtype)

        def body(_, xl):
            xc, lc = xl
            # [chunk, V] fp32, live only inside this scan step
            logits = _dot_f32(xc, wl, (((1,), (1,)), ((), ())))
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            ll = jnp.take_along_axis(logits, lc[:, None], axis=-1)[:, 0]
            return None, jnp.where(lc >= 0, lse - ll, 0.0)

        _, losses = jax.lax.scan(body, None, (xs, labs))
        return losses.reshape(T)

    def fwd(x, w, lab):
        return _loss(x, w, lab), (x, w, lab)

    def bwd(res, g):
        x, w, lab = res
        T, d = x.shape
        n = T // chunk
        xs = x.reshape(n, chunk, d)
        labs = lab.reshape(n, chunk).astype(jnp.int32)
        gs = g.reshape(n, chunk)
        wl = w.astype(x.dtype)

        def body(dw, xlg):
            xc, lc, gc = xlg
            logits = _dot_f32(xc, wl, (((1,), (1,)), ((), ())))
            p = jax.nn.softmax(logits, axis=-1)
            onehot = jax.nn.one_hot(lc, p.shape[-1], dtype=p.dtype)
            mask = (lc >= 0).astype(p.dtype)
            dl = ((p - onehot) * (gc * mask)[:, None]).astype(xc.dtype)
            dxc = dl @ wl  # [chunk, d]
            dw = dw + _dot_f32(dl, xc, (((0,), (0,)), ((), ())))
            return dw, dxc

        dw0 = jnp.zeros(w.shape, jnp.float32)
        dw, dxs = jax.lax.scan(body, dw0, (xs, labs, gs))
        return dxs.reshape(T, d), dw.astype(w.dtype), jnp.zeros_like(lab)

    f.defvjp(fwd, bwd)
    return f


@register(
    "_contrib_fused_lm_head",
    arg_names=["data", "weight", "label"],
    params={"chunk": P("int", 2048)},
)
def _fused_lm_head(attrs, data, weight, label):
    """Per-token softmax cross-entropy of ``data @ weight.T`` against
    integer ``label`` rows, streamed in chunks (see
    :func:`_fused_lm_head_rule`).  ``weight`` uses the FullyConnected
    [num_classes, d] layout so an LM checkpoint's ``pred_weight`` drops
    in unchanged; labels < 0 are ignored (zero loss and gradient).
    Output: [T] fp32 losses."""
    T = data.shape[0]
    chunk = min(int(attrs["chunk"]), T)
    pad = (-T) % chunk
    x = data.reshape(T, -1)
    lab = label.reshape(T)
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad, x.shape[1]), x.dtype)], axis=0)
        lab = jnp.concatenate(
            [lab, jnp.full((pad,), -1.0, lab.dtype)], axis=0)
    out = _fused_lm_head_rule(chunk)(x, weight, lab)
    return out[:T] if pad else out


@register("SoftmaxActivation", params={"mode": P("str", "instance", enum=["instance", "channel"])})
def _softmax_activation(attrs, x):
    if attrs["mode"] == "channel":
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)


def _regression_rule(grad_fn):
    @functools.lru_cache(maxsize=None)
    def make(grad_scale):
        @jax.custom_vjp
        def f(data, label):
            return grad_fn.fwd(data)

        def fwd(data, label):
            out = grad_fn.fwd(data)
            return out, (out, label)

        def bwd(res, g):
            out, label = res
            # reference scales by grad_scale only; batch normalization of the
            # loss is the optimizer's rescale_grad job
            grad = grad_fn.bwd(out, label.reshape(out.shape)) * grad_scale
            return grad, jnp.zeros_like(label)

        f.defvjp(fwd, bwd)
        return f

    return make


class _LinearReg:
    fwd = staticmethod(lambda d: d)
    bwd = staticmethod(lambda o, l: o - l)


class _LogisticReg:
    fwd = staticmethod(jax.nn.sigmoid)
    bwd = staticmethod(lambda o, l: o - l)


class _MAEReg:
    fwd = staticmethod(lambda d: d)
    bwd = staticmethod(lambda o, l: jnp.sign(o - l))


_linear_reg = _regression_rule(_LinearReg)
_logistic_reg = _regression_rule(_LogisticReg)
_mae_reg = _regression_rule(_MAEReg)


@register(
    "LinearRegressionOutput",
    arg_names=["data", "label"],
    params={"grad_scale": P("float", 1.0)},
)
def _linear_regression_output(attrs, data, label):
    return _linear_reg(attrs["grad_scale"])(data, label.astype(data.dtype))


@register(
    "LogisticRegressionOutput",
    arg_names=["data", "label"],
    params={"grad_scale": P("float", 1.0)},
)
def _logistic_regression_output(attrs, data, label):
    return _logistic_reg(attrs["grad_scale"])(data, label.astype(data.dtype))


@register(
    "MAERegressionOutput",
    arg_names=["data", "label"],
    params={"grad_scale": P("float", 1.0)},
)
def _mae_regression_output(attrs, data, label):
    return _mae_reg(attrs["grad_scale"])(data, label.astype(data.dtype))


@functools.lru_cache(maxsize=None)
def _svm_rule(margin, regularization_coefficient, use_linear):
    @jax.custom_vjp
    def f(data, label):
        return data

    def fwd(data, label):
        return data, (data, label)

    def bwd(res, g):
        data, label = res
        lab = label.astype(jnp.int32)
        onehot = jax.nn.one_hot(lab, data.shape[1], dtype=data.dtype)
        sign = 2.0 * onehot - 1.0  # +1 at true class, -1 elsewhere
        viol = (margin - sign * data) > 0
        if use_linear:
            grad = jnp.where(viol, -sign, 0.0)
        else:
            grad = jnp.where(viol, -2.0 * sign * (margin - sign * data), 0.0)
        return grad * regularization_coefficient, jnp.zeros_like(label)

    f.defvjp(fwd, bwd)
    return f


@register(
    "SVMOutput",
    arg_names=["data", "label"],
    params={
        "margin": P("float", 1.0),
        "regularization_coefficient": P("float", 1.0),
        "use_linear": P("bool", False),
    },
)
def _svm_output(attrs, data, label):
    return _svm_rule(
        attrs["margin"], attrs["regularization_coefficient"], attrs["use_linear"]
    )(data, label.astype(data.dtype))


@functools.lru_cache(maxsize=None)
def _make_loss_rule(grad_scale, normalization):
    @jax.custom_vjp
    def f(data):
        return data

    def fwd(data):
        return data, data.shape

    def bwd(shape, g):
        grad = jnp.full(shape, grad_scale)
        if normalization == "batch":
            grad = grad / shape[0]
        return (grad,)

    f.defvjp(fwd, bwd)
    return f


@register(
    "MakeLoss",
    aliases=["make_loss"],
    params={
        "grad_scale": P("float", 1.0),
        "valid_thresh": P("float", 0.0),
        "normalization": P("str", "null", enum=["null", "batch", "valid"]),
    },
)
def _make_loss(attrs, data):
    return _make_loss_rule(attrs["grad_scale"], attrs["normalization"])(data)


@register("BlockGrad", aliases=["stop_gradient"])
def _block_grad(attrs, x):
    return jax.lax.stop_gradient(x)


# ----------------------------------------------------------------------
# Spatial ops
# ----------------------------------------------------------------------


@register(
    "UpSampling",
    variable_args=True,
    params={
        "scale": P("int", 1, required=True),
        "num_filter": P("int", 0),
        "sample_type": P("str", "nearest", enum=["nearest", "bilinear"]),
        "multi_input_mode": P("str", "concat", enum=["concat", "sum"]),
        "num_args": P("int", 1),
        "workspace": P("int", 512),
    },
)
def _upsampling(attrs, *xs):
    s = attrs["scale"]
    outs = []
    for x in xs:
        if attrs["sample_type"] == "nearest":
            up = jnp.repeat(jnp.repeat(x, s, axis=2), s, axis=3)
        else:
            up = jax.image.resize(
                x, x.shape[:2] + (x.shape[2] * s, x.shape[3] * s), method="bilinear"
            )
        outs.append(up)
    if len(outs) == 1:
        return outs[0]
    if attrs["multi_input_mode"] == "sum":
        out = outs[0]
        for o in outs[1:]:
            out = out + o
        return out
    return jnp.concatenate(outs, axis=1)


@register(
    "Pad",
    aliases=["pad"],
    params={
        "mode": P("str", "constant", enum=["constant", "edge", "reflect"]),
        "pad_width": P("shape", None, required=True),
        "constant_value": P("float", 0.0),
    },
)
def _pad(attrs, x):
    pw = attrs["pad_width"]
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    mode = attrs["mode"]
    if mode == "constant":
        return jnp.pad(x, pairs, mode="constant", constant_values=attrs["constant_value"])
    return jnp.pad(x, pairs, mode="edge" if mode == "edge" else "reflect")


@register(
    "Crop",
    variable_args=True,
    params={
        "num_args": P("int", 1),
        "offset": P("shape", (0, 0)),
        "h_w": P("shape", (0, 0)),
        "center_crop": P("bool", False),
    },
)
def _crop(attrs, *xs):
    x = xs[0]
    if len(xs) == 2:
        th, tw = xs[1].shape[2], xs[1].shape[3]
    else:
        th, tw = attrs["h_w"]
    if attrs["center_crop"]:
        oy = (x.shape[2] - th) // 2
        ox = (x.shape[3] - tw) // 2
    else:
        oy, ox = attrs["offset"]
    return x[:, :, oy : oy + th, ox : ox + tw]


# ----------------------------------------------------------------------
# Sequence ops (reference sequence_last/mask/reverse-inl.h).
# Layout matches the reference: (seq_len, batch, ...) by default.
# ----------------------------------------------------------------------


def _seq_args(attrs):
    return (
        ["data", "sequence_length"] if attrs.get("use_sequence_length") else ["data"]
    )


@register(
    "SequenceLast",
    arg_names=["data", "sequence_length"],
    input_names_fn=_seq_args,
    params={"use_sequence_length": P("bool", False)},
)
def _sequence_last(attrs, data, seq_len=None):
    if not attrs["use_sequence_length"] or seq_len is None:
        return data[-1]
    idx = jnp.maximum(seq_len.astype(jnp.int32) - 1, 0)  # (batch,)
    return jnp.take_along_axis(
        data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0
    )[0]


def _seq_last_args(attrs):
    return ["data", "sequence_length"] if attrs.get("use_sequence_length") else ["data"]


@register(
    "SequenceMask",
    arg_names=["data", "sequence_length"],
    input_names_fn=_seq_args,
    params={"use_sequence_length": P("bool", False), "value": P("float", 0.0)},
)
def _sequence_mask(attrs, data, seq_len=None):
    if not attrs["use_sequence_length"] or seq_len is None:
        return data
    steps = jnp.arange(data.shape[0]).reshape((-1, 1))
    mask = steps < seq_len.astype(jnp.int32).reshape((1, -1))
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(attrs["value"], data.dtype))


@register(
    "SequenceReverse",
    arg_names=["data", "sequence_length"],
    input_names_fn=_seq_args,
    params={"use_sequence_length": P("bool", False)},
)
def _sequence_reverse(attrs, data, seq_len=None):
    if not attrs["use_sequence_length"] or seq_len is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T).reshape((-1, 1))
    L = seq_len.astype(jnp.int32).reshape((1, -1))
    rev_idx = jnp.where(steps < L, L - 1 - steps, steps)  # (T, batch)
    return jnp.take_along_axis(
        data, rev_idx.reshape((T, -1) + (1,) * (data.ndim - 2)), axis=0
    )


# ----------------------------------------------------------------------
# ROIPooling / BilinearSampler / GridGenerator / SpatialTransformer
# ----------------------------------------------------------------------


@register(
    "ROIPooling",
    arg_names=["data", "rois"],
    params={
        "pooled_size": P("shape", None, required=True),
        "spatial_scale": P("float", 1.0, required=True),
    },
)
def _roi_pooling(attrs, data, rois):
    ph, pw = attrs["pooled_size"]
    scale = attrs["spatial_scale"]
    H, W = data.shape[2], data.shape[3]

    def pool_one(roi):
        batch_idx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        h = jnp.maximum(y2 - y1 + 1, 1)
        w = jnp.maximum(x2 - x1 + 1, 1)
        img = data[batch_idx]  # (C, H, W)
        ys = jnp.arange(H).reshape(1, -1, 1)
        xs = jnp.arange(W).reshape(1, 1, -1)

        def cell(iy, ix):
            hstart = y1 + (iy * h) // ph
            hend = y1 + ((iy + 1) * h + ph - 1) // ph
            wstart = x1 + (ix * w) // pw
            wend = x1 + ((ix + 1) * w + pw - 1) // pw
            mask = (ys >= hstart) & (ys < hend) & (xs >= wstart) & (xs < wend)
            # empty cells (degenerate/clipped rois) are 0 like the
            # reference (roi_pooling-inl.h is_empty), NOT -inf — an -inf
            # output NaNs the backward and poisons the whole step
            mx_val = jnp.max(jnp.where(mask, img, -jnp.inf), axis=(1, 2))
            return jnp.where(jnp.isfinite(mx_val), mx_val, 0.0)

        cells = [[cell(iy, ix) for ix in range(pw)] for iy in range(ph)]
        out = jnp.stack([jnp.stack(r, axis=-1) for r in cells], axis=-2)
        return out  # (C, ph, pw)

    return jax.vmap(pool_one)(rois)


@register("GridGenerator", arg_names=["data"], params={
    "transform_type": P("str", "affine", enum=["affine", "warp"]),
    "target_shape": P("shape", (0, 0)),
})
def _grid_generator(attrs, data):
    if attrs["transform_type"] == "affine":
        h, w = attrs["target_shape"]
        n = data.shape[0]
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, h*w)
        theta = data.reshape(n, 2, 3)
        grid = jnp.einsum("nij,jk->nik", theta, base)  # (n, 2, h*w)
        return grid.reshape(n, 2, h, w)
    # warp: data is flow (n, 2, h, w)
    n, _, h, w = data.shape
    ys = jnp.arange(h, dtype=data.dtype)
    xs = jnp.arange(w, dtype=data.dtype)
    gx, gy = jnp.meshgrid(xs, ys)
    fx = (gx + data[:, 0]) * 2.0 / jnp.maximum(w - 1, 1) - 1.0
    fy = (gy + data[:, 1]) * 2.0 / jnp.maximum(h - 1, 1) - 1.0
    return jnp.stack([fx, fy], axis=1)


def _bilinear_sample(data, grid):
    """data (n,c,H,W), grid (n,2,h,w) in [-1,1] -> (n,c,h,w)."""
    n, c, H, W = data.shape
    gx = (grid[:, 0] + 1.0) * (W - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (H - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(img, yy, xx):
        # img (c,H,W); yy/xx (h,w) int32
        valid = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
        yc = jnp.clip(yy, 0, H - 1)
        xc = jnp.clip(xx, 0, W - 1)
        vals = img[:, yc, xc]  # (c,h,w)
        return jnp.where(valid, vals, 0.0)

    def sample_one(img, x0_, y0_, wx_, wy_):
        x0i = x0_.astype(jnp.int32)
        y0i = y0_.astype(jnp.int32)
        v00 = gather(img, y0i, x0i)
        v01 = gather(img, y0i, x0i + 1)
        v10 = gather(img, y0i + 1, x0i)
        v11 = gather(img, y0i + 1, x0i + 1)
        return (
            v00 * (1 - wy_) * (1 - wx_)
            + v01 * (1 - wy_) * wx_
            + v10 * wy_ * (1 - wx_)
            + v11 * wy_ * wx_
        )

    return jax.vmap(sample_one)(data, x0, y0, wx, wy)


@register("BilinearSampler", arg_names=["data", "grid"])
def _bilinear_sampler(attrs, data, grid):
    return _bilinear_sample(data, grid)


@register(
    "SpatialTransformer",
    arg_names=["data", "loc"],
    params={
        "target_shape": P("shape", (0, 0)),
        "transform_type": P("str", "affine", enum=["affine"]),
        "sampler_type": P("str", "bilinear", enum=["bilinear"]),
    },
)
def _spatial_transformer(attrs, data, loc):
    grid = _grid_generator(
        {"transform_type": "affine", "target_shape": attrs["target_shape"]}, loc
    )
    return _bilinear_sample(data, grid)


# ----------------------------------------------------------------------
# Correlation (reference src/operator/correlation-inl.h — FlowNet-style
# patch correlation between two feature maps)
# ----------------------------------------------------------------------


@register(
    "Correlation",
    arg_names=["data1", "data2"],
    params={
        "kernel_size": P("int", 1),
        "max_displacement": P("int", 1),
        "stride1": P("int", 1),
        "stride2": P("int", 1),
        "pad_size": P("int", 0),
        "is_multiply": P("bool", True),
    },
)
def _correlation(attrs, data1, data2):
    """Correlation volume: for every displacement d in a (2m+1)^2 grid,
    the K*K*C-normalized patch product (or abs-difference) of data1 and
    shifted data2.  Output (B, D*D, H', W').  Vectorized as a static
    python loop over displacements (the grid is small) with XLA window
    sums — no im2col scratch like the reference's CUDA kernel."""
    K = attrs["kernel_size"]
    md = attrs["max_displacement"]
    s1, s2 = attrs["stride1"], attrs["stride2"]
    pad = attrs["pad_size"]
    B, C, H, W = data1.shape
    rad = (K - 1) // 2
    border = md + rad
    grid_rad = md // s2
    D = 2 * grid_rad + 1
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    Hp, Wp = H + 2 * pad, W + 2 * pad
    out_h = int(_np.ceil((Hp - border * 2) / float(s1)))
    out_w = int(_np.ceil((Wp - border * 2) / float(s1)))
    norm = float(K * K * C)
    window = (1, 1, K, K)
    strides = (1, 1, 1, 1)
    maps = []
    for di in range(-grid_rad, grid_rad + 1):
        for dj in range(-grid_rad, grid_rad + 1):
            dy, dx = di * s2, dj * s2
            shifted = jnp.roll(p2, shift=(-dy, -dx), axis=(2, 3))
            if attrs["is_multiply"]:
                prod = jnp.sum(p1 * shifted, axis=1, keepdims=True)
            else:
                prod = jnp.sum(jnp.abs(p1 - shifted), axis=1, keepdims=True)
            acc = jax.lax.reduce_window(
                prod, 0.0, jax.lax.add, window, strides,
                [(0, 0), (0, 0), (rad, rad), (rad, rad)])
            sl = acc[:, 0, border:border + out_h * s1:s1,
                     border:border + out_w * s1:s1]
            maps.append(sl / norm)
    return jnp.stack(maps, axis=1)


# ----------------------------------------------------------------------
# IdentityAttachKLSparseReg (reference
# src/operator/identity_attach_KL_sparse_reg-inl.h — identity forward,
# KL sparsity penalty injected into backward; moving-average activation)
# ----------------------------------------------------------------------


@register(
    "IdentityAttachKLSparseReg",
    arg_names=["data"],
    aux_names=["moving_avg"],
    params={
        "sparseness_target": P("float", 0.1),
        "penalty": P("float", 0.001),
        "momentum": P("float", 0.9),
    },
    needs_mode=True,
)
def _identity_kl_sparse(attrs, data, moving_avg, is_train=False):
    rho = attrs["sparseness_target"]
    penalty = attrs["penalty"]
    mom = attrs["momentum"]

    @jax.custom_vjp
    def ident(x, avg):
        return x

    def fwd(x, avg):
        return x, (x, avg)

    def bwd(res, dy):
        x, avg = res
        # KL'(rho || rho_hat) per unit, broadcast over the batch
        rho_hat = jnp.clip(avg, 1e-6, 1.0 - 1e-6)
        kl_grad = penalty * (-rho / rho_hat + (1 - rho) / (1 - rho_hat))
        return dy + jnp.broadcast_to(kl_grad, x.shape), None

    ident.defvjp(fwd, bwd)

    batch_mean = jnp.mean(data, axis=0)
    new_avg = jnp.where(
        is_train, mom * moving_avg + (1 - mom) * batch_mean, moving_avg)
    return ident(data, jax.lax.stop_gradient(new_avg)), new_avg
