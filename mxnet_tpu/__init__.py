"""mxnet_tpu — a TPU-native deep learning framework with the capabilities of
MXNet v0.9.x (NDArray+Symbol duality, Module/fit, KVStore, data iterators),
rebuilt on jax/XLA/pjit/Pallas.  See repo README.md and SURVEY.md.

Import as ``import mxnet_tpu as mx`` — the namespace mirrors the reference's
``python/mxnet/__init__.py``.
"""

# Multi-process bootstrap MUST precede any XLA backend touch, so it runs
# before everything else when the launcher env is present (parity: the
# reference's MXInitPSEnv handshake with the dmlc tracker env,
# tools/launch.py → DMLC_PS_ROOT_URI; here tools/launch.py →
# MXNET_TPU_COORDINATOR and jax.distributed).
import os as _os

# Platform forcing: device plugins installed via site hooks can preset
# jax_platforms at interpreter start and ignore the JAX_PLATFORMS env var,
# so a subprocess that explicitly wants the CPU backend (tools, test
# children, the C-API embedded interpreter) can block on a tunneled
# accelerator it never asked for.  MXNET_TPU_PLATFORM is this package's
# unambiguous override: when set, it wins over any preset (jax.config is
# honored as long as no backend is up, and importing this package is
# normally the first backend touch).  JAX_PLATFORMS is still mirrored when
# nothing configured a platform at all.
_plat = _os.environ.get("MXNET_TPU_PLATFORM")
if _plat or _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    try:
        if _plat:
            _jax.config.update("jax_platforms", _plat)
        elif _jax.config.jax_platforms is None:
            _jax.config.update("jax_platforms",
                               _os.environ["JAX_PLATFORMS"])
    except Exception:  # backend already initialized by the host program
        pass

if _os.environ.get("MXNET_TPU_COORDINATOR"):
    import jax as _jax

    # launcher contract: under the coordinator env, JAX_PLATFORMS is an
    # EXPLICIT worker-platform request — force it via config even when a
    # site hook preset a different platform (restores the pre-
    # MXNET_TPU_PLATFORM behavior for external launchers)
    if _os.environ.get("JAX_PLATFORMS") and not _plat:
        try:
            _jax.config.update("jax_platforms",
                               _os.environ["JAX_PLATFORMS"])
        except Exception:
            pass
    _jax.distributed.initialize(
        _os.environ["MXNET_TPU_COORDINATOR"],
        int(_os.environ.get("MXNET_TPU_NUM_PROCS", "1")),
        int(_os.environ.get("MXNET_TPU_PROC_ID", "0")))
    # flag for init_process_group that bootstrap already happened (it must
    # not re-initialize — a second call after backend touch is an error)
    _os.environ["_MXNET_TPU_DIST_READY"] = "1"

from . import base
from .base import MXNetError
from . import context
from .context import Context, cpu, gpu, tpu, current_context, num_tpus
from . import ops
from . import ndarray
from . import ndarray as nd
from . import name
from . import attribute
from .attribute import AttrScope
from . import symbol
from . import symbol as sym
from . import executor
from .executor import Executor
from . import random
from . import random as rnd
from . import io
from . import recordio
from . import initializer
from . import optimizer
from . import optimizer as opt
from . import metric
from . import lr_scheduler
from . import callback
from . import kvstore as kv
from . import kvstore
from . import model
from .model import FeedForward
from . import executor_manager
from . import module
from . import module as mod
from . import monitor
from . import monitor as mon
from .monitor import Monitor
from . import observability
from . import profiler
from . import visualization
from . import visualization as viz
from . import rnn
from . import image as img
from . import image
from . import operator
from .operator import CustomOp, CustomOpProp
from . import predict
from . import deploy
from . import serving
from . import kvstore_server
from . import engine
from . import chaos
from . import rtc
from . import torch_bridge
from . import torch_bridge as th
from . import parallel
from . import stream
from . import deployd
from . import contrib
from . import models
from . import test_utils

__version__ = "0.1.0"

# populate mx.nd.* / mx.sym.* from the op registry (parity:
# _init_ndarray_module / _init_symbol_module)
ndarray._init_module()
symbol._init_module()

# re-export common symbol constructors at top level like the reference
from .symbol import Variable, Group  # noqa: E402
