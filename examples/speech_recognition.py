"""Speech recognition (parity: reference ``example/speech_recognition/``
— ``arch_deepspeech.py``: conv front-end over spectrograms, a
bidirectional GRU stack, per-frame FC, warp-CTC loss; scored by CER in
``stt_metric.py``).

A miniature DeepSpeech-2 on synthetic utterances (no-egress stand-in
for LibriSpeech): each "phoneme" token excites a characteristic
frequency band plus a harmonic for a random 4-6 frame duration, so the
net must both localize tokens in time (CTC alignment) and classify
their spectral signature (conv + BiGRU).  The loss is the built-in
``ctc_loss`` (log-space scan; the reference vendors warp-ctc), and the
gate is greedy-decoded character error rate (edit distance / label
length), exactly the reference's ``stt_metric.py`` accounting.

    python examples/speech_recognition.py [--num-epochs 10]
"""

import argparse
import logging
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

if __name__ == "__main__":
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

import mxnet_tpu as mx

FREQ = 16          # spectrogram bins
T = 40             # frames per utterance
LEN = 4            # tokens per utterance
N_TOK = 5          # token alphabet 1..5 (0 = CTC blank)
N_CLASS = N_TOK + 1


def make_batch(rng, batch):
    """Spectrograms (batch, T, FREQ) + labels (batch, LEN)."""
    spec = rng.uniform(0, 0.2, (batch, T, FREQ)).astype(np.float32)
    labels = np.zeros((batch, LEN), np.float32)
    for b in range(batch):
        toks = rng.randint(0, N_TOK, LEN)
        labels[b] = toks + 1
        t = rng.randint(1, 4)
        for tok in toks:
            dur = rng.randint(4, 7)
            f0 = 1 + 2 * tok            # fundamental band per token
            end = min(t + dur, T)
            spec[b, t:end, f0:f0 + 2] += rng.uniform(0.8, 1.2)
            if f0 + 6 < FREQ:           # harmonic
                spec[b, t:end, f0 + 5:f0 + 7] += rng.uniform(0.3, 0.6)
            t = end + rng.randint(0, 2)
            if t >= T - 4:
                break
    return spec, labels


def get_symbol(num_filter=8, num_hidden=24):
    """Conv front-end -> BiGRU -> per-frame FC -> CTC (+ scores head)."""
    data = mx.sym.Variable("data")              # (B, T, FREQ)
    label = mx.sym.Variable("label")            # (B, LEN)
    img = mx.sym.reshape(data, shape=(-1, 1, T, FREQ))
    net = mx.sym.Convolution(img, num_filter=num_filter, kernel=(5, 3),
                             pad=(2, 1), name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Convolution(net, num_filter=num_filter, kernel=(5, 3),
                             pad=(2, 1), name="conv2")
    net = mx.sym.Activation(net, act_type="relu")
    # (B, C, T, F) -> (B, T, C*F): time stays a sequence axis
    seq = mx.sym.reshape(mx.sym.transpose(net, axes=(0, 2, 1, 3)),
                         shape=(-1, T, num_filter * FREQ))
    bi = mx.rnn.BidirectionalCell(
        mx.rnn.GRUCell(num_hidden=num_hidden, prefix="gru_f_"),
        mx.rnn.GRUCell(num_hidden=num_hidden, prefix="gru_b_"))
    outputs, _ = bi.unroll(T, inputs=seq, layout="NTC",
                           merge_outputs=True)   # (B, T, 2H)
    flat = mx.sym.reshape(outputs, shape=(-1, 2 * num_hidden))
    pred = mx.sym.FullyConnected(flat, num_hidden=N_CLASS, name="cls")
    pred = mx.sym.transpose(
        mx.sym.reshape(pred, shape=(-1, T, N_CLASS)),
        axes=(1, 0, 2))                          # (T, B, C)
    loss = mx.sym.MakeLoss(mx.sym.mean(
        mx.contrib.sym.ctc_loss(pred, label)), name="ctc")
    return mx.sym.Group([loss, mx.sym.BlockGrad(pred, name="scores")])


def greedy_decode(post):
    """(T,B,C) scores -> sequences (collapse repeats, drop blanks)."""
    ids = post.argmax(axis=2)
    out = []
    for b in range(ids.shape[1]):
        seq, prev = [], -1
        for t in range(ids.shape[0]):
            c = int(ids[t, b])
            if c != prev and c != 0:
                seq.append(c)
            prev = c
        out.append(seq)
    return out


def edit_distance(a, b):
    """Levenshtein distance (the reference CER's core)."""
    dp = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        prev, dp[0] = dp[0], i
        for j, cb in enumerate(b, 1):
            prev, dp[j] = dp[j], min(dp[j] + 1, dp[j - 1] + 1,
                                     prev + (ca != cb))
    return dp[len(b)]


def train(num_epochs=10, batch=32, lr=4e-3, seed=0, ctx=None, log=True,
          stop_cer=None):
    ctx = ctx or mx.cpu()
    rng = np.random.RandomState(seed)
    np.random.seed(seed + 1)
    sym = get_symbol()
    ex = sym.simple_bind(
        ctx, data=(batch, T, FREQ), label=(batch, LEN),
        grad_req={n: ("null" if n in ("data", "label") else "write")
                  for n in sym.list_arguments()})
    init = mx.initializer.Xavier()
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "label"):
            init(mx.initializer.InitDesc(name), arr)
    opt = mx.optimizer.Adam(learning_rate=lr)
    updater = mx.optimizer.get_updater(opt)

    cer = 1.0
    for epoch in range(num_epochs):
        edits = chars = 0
        losses = []
        for _ in range(20):
            spec, labels = make_batch(rng, batch)
            ex.arg_dict["data"][:] = spec
            ex.arg_dict["label"][:] = labels
            ex.forward(is_train=True)
            ex.backward()
            for i, name in enumerate(sorted(ex.grad_dict)):
                g = ex.grad_dict[name]
                if g is not None:
                    updater(i, g, ex.arg_dict[name])
            outs = [o.asnumpy() for o in ex.outputs]
            losses.append(float(outs[0].mean()))
            for dec, want in zip(greedy_decode(outs[1]),
                                 labels.astype(int).tolist()):
                edits += edit_distance(dec, want)
                chars += len(want)
        cer = edits / max(chars, 1)
        if log:
            logging.info("epoch %d: ctc_loss=%.3f cer=%.3f",
                         epoch, float(np.mean(losses)), cer)
        if stop_cer is not None and cer <= stop_cer:
            break
    return {"cer": cer}


run = train  # gate-harness entry point


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(description="mini DeepSpeech CTC")
    p.add_argument("--num-epochs", type=int, default=10)
    args = p.parse_args()
    stats = train(num_epochs=args.num_epochs)
    print("final: cer=%.3f" % stats["cer"])


if __name__ == "__main__":
    main()
