"""Matrix-factorization recommender (parity: reference
``example/recommenders/`` — `demo1-MF`: user/item embeddings whose dot
product predicts ratings, trained with a regression head).

Synthetic ratings (no-egress fallback): a ground-truth low-rank
user/item factor model plus noise.  The gate requires the learned model
to approach the noise floor and clearly beat the global-mean and
per-item-bias baselines — i.e. the embeddings carry real collaborative
signal.

    python examples/recommender_mf.py [--epochs 15]
"""

import argparse
import logging
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

import mxnet_tpu as mx

USERS, ITEMS, RANK = 120, 80, 5
NOISE = 0.25


def make_data(rng, n):
    u_factors = rng.randn(USERS, RANK) * 0.8
    i_factors = rng.randn(ITEMS, RANK) * 0.8
    users = rng.randint(0, USERS, n)
    items = rng.randint(0, ITEMS, n)
    ratings = (np.sum(u_factors[users] * i_factors[items], axis=1)
               + NOISE * rng.randn(n))
    return users, items, ratings.astype(np.float32)


def get_symbol(dim=8):
    user = mx.sym.Variable("user")
    item = mx.sym.Variable("item")
    score = mx.sym.Variable("score")
    u_emb = mx.sym.Embedding(user, input_dim=USERS, output_dim=dim,
                             name="user_embed")
    i_emb = mx.sym.Embedding(item, input_dim=ITEMS, output_dim=dim,
                             name="item_embed")
    pred = mx.sym.sum_axis(u_emb * i_emb, axis=1)
    return mx.sym.LinearRegressionOutput(pred, score, name="lro")


def run(epochs=15, batch=64, seed=0, log=True):
    rng = np.random.RandomState(seed)
    np.random.seed(seed + 1)
    # one factor model; train/val split over observed entries
    users, items, ratings = make_data(rng, 8000)
    tr, va = slice(0, 7000), slice(7000, None)

    mod = mx.mod.Module(get_symbol(), context=mx.cpu(),
                        data_names=("user", "item"), label_names=("score",))
    it = mx.io.NDArrayIter({"user": users[tr].astype(np.float32),
                            "item": items[tr].astype(np.float32)},
                           {"score": ratings[tr]},
                           batch_size=batch, shuffle=True, seed=3)
    mod.fit(it, num_epoch=epochs, optimizer="adam",
            optimizer_params={"learning_rate": 5e-3, "wd": 1e-5},
            initializer=mx.initializer.Normal(0.1))

    val = mx.io.NDArrayIter({"user": users[va].astype(np.float32),
                             "item": items[va].astype(np.float32)},
                            {"score": ratings[va]}, batch_size=batch)
    pred = mod.predict(val).asnumpy().ravel()
    truth = ratings[va][:len(pred)]
    rmse = float(np.sqrt(np.mean((pred - truth) ** 2)))

    # baselines: global mean, and per-item mean rating
    gmean = ratings[tr].mean()
    rmse_global = float(np.sqrt(np.mean((truth - gmean) ** 2)))
    item_mean = np.full(ITEMS, gmean, np.float32)
    for j in range(ITEMS):
        mask = items[tr] == j
        if mask.any():
            item_mean[j] = ratings[tr][mask].mean()
    rmse_item = float(np.sqrt(np.mean(
        (truth - item_mean[items[va][:len(pred)]]) ** 2)))
    if log:
        logging.info("rmse: mf=%.3f item-mean=%.3f global=%.3f "
                     "(noise floor %.2f)", rmse, rmse_item, rmse_global,
                     NOISE)
    return {"rmse": rmse, "rmse_item": rmse_item,
            "rmse_global": rmse_global}


def main():
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    args = ap.parse_args()
    stats = run(epochs=args.epochs)
    print("recommender_mf: rmse=%.3f (item-mean %.3f, global %.3f)"
          % (stats["rmse"], stats["rmse_item"], stats["rmse_global"]))


if __name__ == "__main__":
    main()
